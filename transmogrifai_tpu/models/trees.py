"""Tree ensembles — the TPU-native re-design of the reference's Spark MLlib
tree wrappers (core/.../impl/classification/OpRandomForestClassifier.scala:58,
OpGBTClassifier.scala, OpDecisionTreeClassifier.scala, impl/regression/
OpRandomForestRegressor.scala, OpGBTRegressor.scala, OpXGBoostClassifier.scala:47).

Architecture (LightGBM-style, built for the MXU/HBM rather than translated
from Spark's per-partition `findBestSplits`):

* features are quantile-binned once into a compact int matrix ``B [N, D]``
  (int8 when bins fit, else int32) held in
  HBM — every tree/round reuses it;
* trees grow level-wise with **static shapes**: level ``l`` has ``2^l`` nodes,
  per-(node, feature, bin) statistics are built with ``jax.ops.segment_sum``
  scanned over feature chunks (bounded memory), split gains for all bins come
  from one cumulative sum;
* a whole random forest trains as a single XLA program — ``vmap`` over trees
  with Poisson-bootstrap row weights and random feature masks (the TPU
  equivalent of Spark's distributed per-tree jobs, SURVEY.md §2.6 P3);
* gradient boosting scans rounds, computing grad/hess on device and fitting
  each tree to them (XGBoost-style second-order gains).

Trees are stored as perfect-heap arrays (feature, threshold, is_leaf,
leaf_value), so batch prediction is ``max_depth`` gathers — no recursion.
"""

from __future__ import annotations

import functools
import math
from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..columns import device_matrix, to_device_f32
from .base import PredictionModel, PredictorEstimator

MAX_BINS_DEFAULT = 32


def mxu_dtype_for(platform: str):
    """Histogram-matmul dtype for a device platform: bf16 hits the MXU on TPU;
    the CPU backend lacks BF16xBF16=F32 dot support, so f32 there."""
    return jnp.float32 if platform == "cpu" else jnp.bfloat16


def _mxu_dtype():
    """Default histogram dtype from the process-global backend.  NOT cached:
    the backend can change mid-process (dryrun_multichip switches from the
    real chip to a virtual CPU mesh).  Computations pinned to an explicit
    mesh should instead pass ``hist_dtype=mxu_dtype_for(<mesh platform>)``."""
    return mxu_dtype_for(jax.default_backend())


# --------------------------------------------------------------------------
# binning
# --------------------------------------------------------------------------

# (weakref(X), {max_bins: (splits, B)}) keyed by id(X): every tree family in
# a CV grid shares ONE binned matrix per (matrix, max_bins) instead of each
# building its own — at 11M rows a duplicate B is ~0.3 GB of HBM and a full
# binning pass, and cumulative residency is what hard-faults the worker
# (VERDICT r3 #2).  Entries drop when the feature matrix is collected.
_SHARED_BINS: Dict[int, Any] = {}

# id(X) → (weakref(X), n_real) for zero-weight-padded matrices: the sweep's
# fit-shape padding (tuning.register_real_rows) appends all-zero rows whose
# fold weight is 0 everywhere.  Every tree statistic is sample-weighted, so
# those rows already contribute nothing to fits — but the UNWEIGHTED
# quantile sketch in build_bin_splits would see them as a spike at 0 and
# shift every split point.  Registering the true row count keeps padded
# binning bit-identical to the unpadded fit.
_REAL_ROWS: Dict[int, Any] = {}


def register_real_rows(X, n_real: int) -> None:
    """Mark ``X`` as padded: only its first ``n_real`` rows are data."""
    import weakref
    key = id(X)
    try:
        ref = weakref.ref(X, lambda _r, _k=key: _REAL_ROWS.pop(_k, None))
    except TypeError:
        return
    _REAL_ROWS[key] = (ref, int(n_real))


def real_rows(X) -> int:
    """The number of true data rows in ``X`` (== len(X) unless padded)."""
    ent = _REAL_ROWS.get(id(X))
    if ent is not None and ent[0]() is X:
        return min(int(ent[1]), X.shape[0])
    return X.shape[0]


def shared_binned(X, max_bins: int):
    """(splits, B) for a device matrix, cached across model families."""
    import weakref

    key = id(X)
    ent = _SHARED_BINS.get(key)
    if ent is not None and ent[0]() is X and max_bins in ent[1]:
        return ent[1][max_bins]
    Xj = device_matrix(X)
    sp = build_bin_splits(X, max_bins)
    B = bin_data(Xj, jnp.asarray(sp))
    if ent is None or ent[0]() is not X:
        try:
            ref = weakref.ref(X, lambda _r, _k=key: _SHARED_BINS.pop(_k, None))
        except TypeError:
            return sp, B
        ent = (ref, {})
        _SHARED_BINS[key] = ent
    ent[1][max_bins] = (sp, B)
    return sp, B


def build_bin_splits(X: np.ndarray, max_bins: int = MAX_BINS_DEFAULT) -> np.ndarray:
    """Per-feature quantile split points → [D, max_bins-1] float32, padded
    with +inf (≙ Spark's findSplits quantile sketch).  Device-resident inputs
    are quantiled on device — only the tiny [D, B] result crosses the link."""
    n, d = X.shape
    qs = np.linspace(0, 1, max_bins + 1)[1:-1]
    # padded matrices: sketch quantiles over the true rows only (the
    # zero-weight padding tail would otherwise shift every split point)
    n_q = real_rows(X)
    Xq = X[:n_q] if n_q < n else X
    if isinstance(X, jax.Array):
        splits = np.asarray(jnp.quantile(
            Xq, jnp.asarray(qs, jnp.float32), axis=0)).T.astype(np.float32)
    else:
        splits = np.quantile(Xq, qs, axis=0).T.astype(np.float32)  # [D, max_bins-1]
    # dedupe per row; pad with +inf so empty bins are harmless
    out = np.full((d, max_bins - 1), np.inf, dtype=np.float32)
    for j in range(d):
        u = np.unique(splits[j])
        u = u[np.isfinite(u)]
        out[j, :len(u)] = u
    return out


@jax.jit
def bin_data(X: jnp.ndarray, splits: jnp.ndarray) -> jnp.ndarray:
    """bin b of x = number of split points < x  → int32 [N, D].

    Chunked over rows: the one-shot broadcast materializes an [N, D, bins]
    boolean — ~9.5 GB at 11M x 28 x 31, which hard-faults a 16 GB worker.
    Row chunks keep the transient under ~1 GB while producing the same
    device-resident [N, D] result.  Bin ids store as int8 when they fit
    (max_bins ≤ 127 always holds for the reference's MaxBin=32 default) —
    the binned matrix and its padded/chunked views are the largest resident
    tree buffers at 10M+ rows."""
    n, d = X.shape
    nb = splits.shape[1]
    dt = jnp.int8 if nb < 127 else jnp.int32
    limit = 1 << 28                      # transient bool elements per chunk
    rows = max(1, limit // max(d * nb, 1))
    if n <= rows:
        return jnp.sum(X[:, :, None] > splits[None, :, :],
                       axis=-1).astype(dt)
    # lax.map keeps the traced body constant-size regardless of N (a python
    # loop of slices would grow the HLO linearly with the chunk count)
    n_blocks = -(-n // rows)
    pad = n_blocks * rows - n
    Xp = jnp.pad(X, ((0, pad), (0, 0))).reshape(n_blocks, rows, d)
    out = jax.lax.map(
        lambda xb: jnp.sum(xb[:, :, None] > splits[None, :, :],
                           axis=-1).astype(dt), Xp)
    return out.reshape(n_blocks * rows, d)[:n]


# --------------------------------------------------------------------------
# single-tree fit (jittable, vmappable over trees)
# --------------------------------------------------------------------------

class TreeArrays(NamedTuple):
    feature: jnp.ndarray    # [T] int32 (split feature; -1 at pure leaves)
    threshold: jnp.ndarray  # [T] float32 (raw split threshold)
    is_leaf: jnp.ndarray    # [T] bool
    leaf: jnp.ndarray       # [T, V] float32 leaf values
    gain: jnp.ndarray       # [D] per-feature impurity-gain sum over splits
                            # (count-weighted, ≙ Spark featureImportances /
                            # ModelInsights.scala:74-392 contributions)


def _gain_variance(left, right, parent, lam):
    """Variance-impurity gain (Spark 'variance'); stats = [count, wy, wy2]."""
    def sse(s):
        cnt = jnp.maximum(s[..., 0], 1e-12)
        return s[..., 2] - s[..., 1] ** 2 / cnt
    return sse(parent) - sse(left) - sse(right)


def _gain_gini(left, right, parent, lam):
    """Gini-impurity gain; stats = [count, class_0 .. class_{C-1}]."""
    def wgini(s):
        cnt = jnp.maximum(s[..., 0], 1e-12)
        return cnt * (1.0 - jnp.sum((s[..., 1:] / cnt[..., None]) ** 2, axis=-1))
    return wgini(parent) - wgini(left) - wgini(right)


def _gain_xgb(left, right, parent, lam):
    """Second-order gain; stats = [count, G, H]."""
    def score(s):
        return s[..., 1] ** 2 / (s[..., 2] + lam)
    return 0.5 * (score(left) + score(right) - score(parent))


_GAINS = {"variance": _gain_variance, "gini": _gain_gini, "xgb": _gain_xgb}


def _leaf_variance(s):
    return (s[..., 1:2] / jnp.maximum(s[..., 0:1], 1e-12))


def _leaf_gini(s):
    return s[..., 1:] / jnp.maximum(s[..., 0:1], 1e-12)


def _leaf_xgb(s, lam=1.0):
    return -(s[..., 1:2] / (s[..., 2:3] + lam))


def fit_tree(B: jnp.ndarray, splits: jnp.ndarray, stats: jnp.ndarray,
             feature_mask: jnp.ndarray, *, impurity: str, max_depth: int,
             n_bins: int, min_instances: jnp.ndarray, min_gain: jnp.ndarray,
             lam: jnp.ndarray, chunk: "Optional[int]" = None,
             hist_dtype=None, node_feature_key=None,
             features_per_node: "Optional[int]" = None) -> TreeArrays:
    """Grow one tree level-wise on binned data (see ``_fit_tree_unrolled``).

    Dispatches to a compact ``fori_loop``-over-levels implementation when the
    whole tree fits the matmul-histogram path (``max_depth <= 7``): one traced
    level body instead of ``max_depth`` unrolled ones → ~6x smaller HLO, which
    is what dominates wall-clock here (XLA compile + executable (de)serial-
    isation far outweigh device execution for these programs)."""
    S = stats.shape[1]
    P_n = max(1, 2 ** (max_depth - 1))
    if max_depth <= 7 and P_n * S <= 256:
        return _fit_tree_compact(
            B, splits, stats, feature_mask, impurity=impurity,
            max_depth=max_depth, n_bins=n_bins, min_instances=min_instances,
            min_gain=min_gain, lam=lam, chunk=chunk, hist_dtype=hist_dtype,
            node_feature_key=node_feature_key,
            features_per_node=features_per_node)
    return _fit_tree_unrolled(
        B, splits, stats, feature_mask, impurity=impurity,
        max_depth=max_depth, n_bins=n_bins, min_instances=min_instances,
        min_gain=min_gain, lam=lam, chunk=chunk, hist_dtype=hist_dtype,
        node_feature_key=node_feature_key, features_per_node=features_per_node)


def _chunk_prologue(B, feature_mask, splits, n_bins, chunk):
    """Shared feature-chunking prologue of the tree fitters: pad D to a chunk
    multiple and expose [n_chunks, chunk, N] views (bounds the one-hot
    histogram working set to ~chunk * N * n_bins bf16 per lane)."""
    N, D = B.shape
    if chunk is None:
        chunk = max(1, min(32, (512 << 20) // max(N * n_bins * 2, 1)))
    n_chunks = math.ceil(D / chunk)
    D_pad = n_chunks * chunk
    pad = D_pad - D
    B_pad = jnp.pad(B, ((0, 0), (0, pad)))                   # [N, D_pad]
    fmask = jnp.pad(feature_mask, (0, pad))                  # [D_pad]
    B_chunks = B_pad.T.reshape(n_chunks, chunk, N)
    m_chunks = fmask.reshape(n_chunks, chunk)
    splits_pad = (jnp.pad(splits, ((0, pad), (0, 0)), constant_values=np.inf)
                  if pad else splits)
    base_idxs = jnp.arange(n_chunks, dtype=jnp.int32) * chunk
    return (chunk, n_chunks, D_pad, pad, B_pad, fmask, B_chunks, m_chunks,
            splits_pad, base_idxs)


def _fit_tree_compact(B: jnp.ndarray, splits: jnp.ndarray, stats: jnp.ndarray,
                      feature_mask: jnp.ndarray, *, impurity: str,
                      max_depth: int, n_bins: int, min_instances: jnp.ndarray,
                      min_gain: jnp.ndarray, lam: jnp.ndarray,
                      chunk: "Optional[int]" = None, hist_dtype=None,
                      node_feature_key=None,
                      features_per_node: "Optional[int]" = None) -> TreeArrays:
    """``fit_tree`` with ONE traced level body under ``lax.fori_loop``.

    Rows carry their node as a HEAP id; every level works on a fixed padded
    node window of ``P_n = 2^(max_depth-1)`` slots starting at the level
    offset.  Writes use ``dynamic_update_slice`` of static size ``P_n`` at the
    (traced) offset — a level may scribble into the next level's slots, but
    each heap slot's OWN level is always the last writer, so the final arrays
    are exact.  Rows whose node became a leaf simply keep a node id below the
    current level offset and drop out of the one-hot contractions.
    """
    N, D = B.shape
    S = stats.shape[1]
    gain_fn = _GAINS[impurity]
    leaf_fn = {"variance": _leaf_variance, "gini": _leaf_gini,
               "xgb": lambda s: _leaf_xgb(s, lam)}[impurity]
    V = {"variance": 1, "gini": S - 1, "xgb": 1}[impurity]
    T = 2 ** (max_depth + 1) - 1
    P_n = max(1, 2 ** (max_depth - 1))
    mxu = hist_dtype if hist_dtype is not None else _mxu_dtype()

    (chunk, n_chunks, D_pad, pad, B_pad, fmask, B_chunks, m_chunks,
     splits_pad, base_idxs) = _chunk_prologue(B, feature_mask, splits,
                                              n_bins, chunk)
    subset = (node_feature_key is not None and features_per_node is not None
              and features_per_node < D)

    def level_body(lvl, carry):
        feat_arr, thr_arr, leaf_flag, leaf_val, row_node, gain_acc = carry
        offset = (1 << lvl) - 1                              # traced
        nodes = offset + jnp.arange(P_n, dtype=jnp.int32)
        # routing one-hot in MXU dtype: [N, P_n] is GBs at 10M+ rows and
        # deep windows; 0/1 is exact in bf16 and both consumers accumulate f32
        oh = (row_node[:, None] == nodes[None, :]).astype(mxu)
        node_stats = jnp.einsum("np,ns->ps", oh, stats,
                                preferred_element_type=jnp.float32)
        lv = leaf_fn(node_stats).astype(jnp.float32)
        leaf_val2 = jax.lax.dynamic_update_slice(leaf_val, lv, (offset, 0))

        if subset:
            kl = jax.random.fold_in(node_feature_key, lvl)
            scores = jax.random.uniform(kl, (P_n, D_pad))
            scores = jnp.where(fmask[None, :] > 0, scores, jnp.inf)
            kth = jnp.sort(scores, axis=1)[:, features_per_node - 1][:, None]
            nm_chunks = (scores <= kth).T.reshape(n_chunks, chunk, P_n)
        else:
            nm_chunks = jnp.ones((n_chunks, chunk, P_n), bool)

        P = (oh[:, :, None] * stats[:, None, :]).reshape(
            N, P_n * S).astype(mxu)

        def scan_chunk(c, xs):
            best_gain, best_feat, best_bin = c
            bc, mc, nmc, base_idx = xs
            ohb = (bc[:, :, None] == jnp.arange(n_bins)[None, None, :]
                   ).astype(mxu)                             # [chunk, N, n_bins]
            hist = jnp.einsum("cnb,nk->ckb", ohb, P,
                              preferred_element_type=jnp.float32)
            hist = hist.reshape(chunk, P_n, S, n_bins).transpose(0, 1, 3, 2)
            left = jnp.cumsum(hist, axis=2)                  # [chunk, P_n, n_bins, S]
            right = node_stats[None, :, None, :] - left
            gains = gain_fn(left, right, node_stats[None, :, None, :], lam)
            ok = ((left[..., 0] >= min_instances) &
                  (right[..., 0] >= min_instances) &
                  mc[:, None, None] & nmc[:, :, None] &
                  (jnp.arange(n_bins)[None, None, :] < n_bins - 1))
            gains = jnp.where(ok, gains, -jnp.inf)           # [chunk, P_n, n_bins]
            cg = jnp.max(gains, axis=2)
            cb = jnp.argmax(gains, axis=2).astype(jnp.int32)
            fg = jnp.max(cg, axis=0)                         # [P_n]
            fi = jnp.argmax(cg, axis=0)
            fb = jnp.take_along_axis(cb, fi[None, :], axis=0)[0]
            better = fg > best_gain
            best_gain = jnp.where(better, fg, best_gain)
            best_feat = jnp.where(better, base_idx + fi.astype(jnp.int32),
                                  best_feat)
            best_bin = jnp.where(better, fb, best_bin)
            return (best_gain, best_feat, best_bin), None

        init = (jnp.full((P_n,), -jnp.inf, jnp.float32),
                jnp.zeros((P_n,), jnp.int32), jnp.zeros((P_n,), jnp.int32))
        (best_gain, best_feat, best_bin), _ = jax.lax.scan(
            scan_chunk, init, (B_chunks, m_chunks, nm_chunks, base_idxs))

        node_is_leaf = (best_gain <= min_gain) | (~jnp.isfinite(best_gain))
        thr = splits_pad[best_feat,
                         jnp.clip(best_bin, 0, splits.shape[1] - 1)]
        feat_arr2 = jax.lax.dynamic_update_slice(
            feat_arr, jnp.where(node_is_leaf, -1, best_feat), (offset,))
        thr_arr2 = jax.lax.dynamic_update_slice(thr_arr, thr, (offset,))
        leaf_flag2 = jax.lax.dynamic_update_slice(
            leaf_flag, node_is_leaf, (offset,))

        # route rows through their node's split (one-hot contractions; rows
        # not at this level match nothing and stay put)
        f_of_row = (oh @ best_feat.astype(jnp.float32)).astype(jnp.int32)
        bin_of_row = oh @ best_bin.astype(jnp.float32)
        dead_of_row = oh @ node_is_leaf.astype(jnp.float32)
        at_level = jnp.sum(oh.astype(jnp.float32), axis=1) > 0.5
        # per-feature gain accumulation for importances: only nodes that
        # actually split contribute (zero-row window slots and pruned nodes
        # carry -inf/min gains and are excluded by node_is_leaf)
        gain_acc2 = gain_acc.at[best_feat].add(
            jnp.where(node_is_leaf, 0.0, best_gain))
        # per-row bin of the split feature: a [N] gather beats the [N, D]
        # one-hot einsum it replaces (two full-matrix f32 transients)
        b_of_row = jnp.take_along_axis(
            B_pad, f_of_row[:, None], axis=1)[:, 0].astype(jnp.float32)
        go_right = (b_of_row > bin_of_row).astype(jnp.int32)
        child = 2 * row_node + 1 + go_right
        advance = at_level & (dead_of_row < 0.5)
        row_node2 = jnp.where(advance, child, row_node)
        return (feat_arr2, thr_arr2, leaf_flag2, leaf_val2, row_node2,
                gain_acc2)

    init = (jnp.full((T,), -1, jnp.int32),
            jnp.full((T,), jnp.inf, jnp.float32),
            jnp.zeros((T,), bool),
            jnp.zeros((T, V), jnp.float32),
            jnp.zeros((N,), jnp.int32),
            jnp.zeros((D_pad,), jnp.float32))
    (feat_arr, thr_arr, leaf_flag, leaf_val, row_node,
     gain_acc) = jax.lax.fori_loop(0, max_depth, level_body, init)

    # epilogue: the bottom level is all leaves (static offset/shape)
    n_last = 2 ** max_depth
    off = n_last - 1
    nodes = off + jnp.arange(n_last, dtype=jnp.int32)
    oh = (row_node[:, None] == nodes[None, :]).astype(mxu)
    node_stats = jnp.einsum("np,ns->ps", oh, stats,
                            preferred_element_type=jnp.float32)
    lv = leaf_fn(node_stats).astype(jnp.float32)
    leaf_val = leaf_val.at[off:].set(lv)
    leaf_flag = leaf_flag.at[off:].set(True)
    feat_arr = feat_arr.at[off:].set(-1)
    thr_arr = thr_arr.at[off:].set(jnp.inf)
    return TreeArrays(feat_arr, thr_arr, leaf_flag, leaf_val, gain_acc[:D])


def _fit_tree_unrolled(B: jnp.ndarray, splits: jnp.ndarray, stats: jnp.ndarray,
                       feature_mask: jnp.ndarray, *, impurity: str,
                       max_depth: int, n_bins: int, min_instances: jnp.ndarray,
                       min_gain: jnp.ndarray, lam: jnp.ndarray,
                       chunk: "Optional[int]" = None, hist_dtype=None,
                       node_feature_key=None,
                       features_per_node: "Optional[int]" = None) -> TreeArrays:
    """Grow one tree level-wise on binned data.

    B [N, D] int (int8/int32 bin ids); stats [N, S] pre-weighted per-row statistics (col 0 must be
    the row weight/count); feature_mask [D] 0/1.  Returns perfect-heap arrays
    with ``T = 2^(max_depth+1) - 1`` nodes.

    ``node_feature_key`` + ``features_per_node`` enable random-forest PER-NODE
    feature subsetting (Spark's featureSubsetStrategy / sklearn max_features
    semantics): every node at every level draws its own candidate-feature set.
    Restricting whole TREES to a feature subset instead cripples interaction
    learning — with D features and k per tree, almost no tree holds all the
    interacting features together.

    Histogram strategy (the TPU-critical choice): for shallow levels
    (``n_l * S <= 256``) the per-(node, feature, bin) stats come from one bf16
    matmul on the MXU — ``(onehot_node x stats)^T @ onehot_bins`` — instead of
    scatter-adds, which XLA lowers to sorts on TPU.  Deep levels (only
    ``max_depth > 7``-ish trees reach them) fall back to per-stat segment-sums.

    ``hist_dtype`` pins the histogram-matmul dtype; callers running on an
    explicit device mesh should pass ``mxu_dtype_for(platform)`` of the mesh's
    platform — the default consults the process-global default backend, which
    can differ from the mesh (e.g. a CPU mesh under a TPU default backend).
    """
    N, D = B.shape
    S = stats.shape[1]
    gain_fn = _GAINS[impurity]
    leaf_fn = {"variance": _leaf_variance, "gini": _leaf_gini,
               "xgb": lambda s: _leaf_xgb(s, lam)}[impurity]
    V = {"variance": 1, "gini": S - 1, "xgb": 1}[impurity]
    T = 2 ** (max_depth + 1) - 1

    (chunk, n_chunks, D_pad, pad, B_pad, fmask, B_chunks, m_chunks,
     splits_pad, base_idxs) = _chunk_prologue(B, feature_mask, splits,
                                              n_bins, chunk)

    feat_arr = jnp.full((T,), -1, jnp.int32)
    thr_arr = jnp.full((T,), jnp.inf, jnp.float32)
    leaf_flag = jnp.zeros((T,), bool)
    leaf_val = jnp.zeros((T, V), jnp.float32)

    row_node = jnp.zeros((N,), jnp.int32)
    parent_dead = jnp.zeros((1,), bool)  # nodes whose ancestor is a leaf
    gain_acc = jnp.zeros((D_pad,), jnp.float32)

    for level in range(max_depth + 1):
        n_l = 2 ** level
        offset = n_l - 1
        if n_l <= 128:
            # one-hot matmul instead of segment_sum: TPU lowers scatter-adds
            # to sorts and the gather/scatter forms compile pathologically
            oh_stats = (row_node[:, None] == jnp.arange(n_l)[None, :]
                        ).astype(jnp.float32)
            node_stats = jnp.einsum("nk,ns->ks", oh_stats, stats)
        else:
            node_stats = jax.ops.segment_sum(stats, row_node,
                                             num_segments=n_l)
        lv = leaf_fn(node_stats)
        leaf_val = jax.lax.dynamic_update_slice(leaf_val, lv.astype(jnp.float32),
                                                (offset, 0))
        if level == max_depth:
            leaf_flag = jax.lax.dynamic_update_slice(
                leaf_flag, jnp.ones((n_l,), bool), (offset,))
            break

        use_matmul = n_l * S <= 256
        mxu = hist_dtype if hist_dtype is not None else _mxu_dtype()
        # per-node candidate-feature masks [n_chunks, chunk, n_l]: each node
        # draws its own subset (uniform scores, k-th order-statistic cut)
        if (node_feature_key is not None and features_per_node is not None
                and features_per_node < D):
            kl = jax.random.fold_in(node_feature_key, level)
            scores = jax.random.uniform(kl, (n_l, D_pad))
            scores = jnp.where(fmask[None, :] > 0, scores, jnp.inf)
            kth = jnp.sort(scores, axis=1)[:, features_per_node - 1][:, None]
            node_mask = scores <= kth                        # [n_l, D_pad]
            nm_chunks = node_mask.T.reshape(n_chunks, chunk, n_l)
        else:
            nm_chunks = jnp.ones((n_chunks, chunk, n_l), bool)
        if use_matmul:
            # P [N, n_l*S]: each row's stats routed to its node's slot;
            # the histogram then is one MXU matmul against one-hot bins
            oh_node = row_node[:, None] == jnp.arange(n_l)[None, :]
            P = (oh_node[:, :, None] * stats[:, None, :]).reshape(
                N, n_l * S).astype(mxu)

        def chunk_hist(bc):
            """[chunk, N] bins → [chunk, n_l, n_bins, S] histogram."""
            if use_matmul:
                oh = (bc[:, :, None] == jnp.arange(n_bins)[None, None, :]
                      ).astype(mxu)                          # [chunk, N, n_bins]
                hist = jnp.einsum("cnb,nk->ckb", oh, P,
                                  preferred_element_type=jnp.float32)
                return hist.reshape(chunk, n_l, S, n_bins).transpose(0, 1, 3, 2)
            seg = row_node[None, :] * n_bins + bc            # [chunk, N]

            # one 1-D segment-sum per stat component: every large tensor here
            # is [chunk, N] (N minormost), never [.., S] — a small-S minormost
            # dim would be padded to the 128-lane TPU tile (42x HBM blowup)
            def hist_for_stat(srow):
                return jax.vmap(lambda ids: jax.ops.segment_sum(
                    srow, ids, num_segments=n_l * n_bins))(seg)  # [chunk, nlb]

            hist = jnp.stack([hist_for_stat(stats[:, s]) for s in range(S)],
                             axis=-1)                        # [chunk, nlb, S]
            return hist.reshape(chunk, n_l, n_bins, S)

        def scan_chunk(carry, xs):
            best_gain, best_feat, best_bin = carry
            bc, mc, nmc, base_idx = xs      # [chunk, N], [chunk], [chunk, n_l]
            hist = chunk_hist(bc)
            left = jnp.cumsum(hist, axis=2)                  # [chunk, n_l, n_bins, S]
            right = node_stats[None, :, None, :] - left
            gains = gain_fn(left, right, node_stats[None, :, None, :], lam)
            ok = ((left[..., 0] >= min_instances) &
                  (right[..., 0] >= min_instances) &
                  mc[:, None, None] & nmc[:, :, None] &
                  (jnp.arange(n_bins)[None, None, :] < n_bins - 1))
            gains = jnp.where(ok, gains, -jnp.inf)           # [chunk, n_l, n_bins]
            cg = jnp.max(gains, axis=2)                      # [chunk, n_l]
            cb = jnp.argmax(gains, axis=2).astype(jnp.int32)
            fg = jnp.max(cg, axis=0)                         # [n_l]
            fi = jnp.argmax(cg, axis=0)                      # [n_l] chunk-local feat
            fb = jnp.take_along_axis(cb, fi[None, :], axis=0)[0]
            better = fg > best_gain
            best_gain = jnp.where(better, fg, best_gain)
            best_feat = jnp.where(better, base_idx + fi.astype(jnp.int32), best_feat)
            best_bin = jnp.where(better, fb, best_bin)
            return (best_gain, best_feat, best_bin), None

        init = (jnp.full((n_l,), -jnp.inf, jnp.float32),
                jnp.zeros((n_l,), jnp.int32), jnp.zeros((n_l,), jnp.int32))
        (best_gain, best_feat, best_bin), _ = jax.lax.scan(
            scan_chunk, init, (B_chunks, m_chunks, nm_chunks, base_idxs))

        node_is_leaf = (best_gain <= min_gain) | (~jnp.isfinite(best_gain)) | parent_dead
        gain_acc = gain_acc.at[best_feat].add(
            jnp.where(node_is_leaf, 0.0, best_gain))
        thr = splits_pad[best_feat, jnp.clip(best_bin, 0, splits.shape[1] - 1)]
        feat_arr = jax.lax.dynamic_update_slice(
            feat_arr, jnp.where(node_is_leaf, -1, best_feat), (offset,))
        thr_arr = jax.lax.dynamic_update_slice(thr_arr, thr, (offset,))
        leaf_flag = jax.lax.dynamic_update_slice(leaf_flag, node_is_leaf, (offset,))

        # route rows: bin(feature of my node) > split bin → right child.
        # All lookups are fused one-hot contractions — no per-row gathers
        # (same TPU pathology as in predict_trees_raw); bins/feat ids are
        # small integers, exact in float32
        oh_rows = (row_node[:, None] == jnp.arange(n_l)[None, :]
                   ).astype(jnp.float32)
        f_of_row = (oh_rows @ best_feat.astype(jnp.float32)).astype(jnp.int32)
        bin_of_row = oh_rows @ best_bin.astype(jnp.float32)
        f_oh = (f_of_row[:, None] == jnp.arange(D_pad)[None, :]
                ).astype(jnp.float32)
        b_of_row = jnp.einsum("nd,nd->n", f_oh, B_pad.astype(jnp.float32))
        go_right = b_of_row > bin_of_row
        row_node = 2 * row_node + go_right.astype(jnp.int32)
        parent_dead = jnp.repeat(node_is_leaf, 2)

    return TreeArrays(feat_arr, thr_arr, leaf_flag, leaf_val, gain_acc[:D])


@functools.partial(jax.jit, static_argnames=("max_depth",))
def predict_trees_raw(X: jnp.ndarray, feature: jnp.ndarray, threshold: jnp.ndarray,
                      is_leaf: jnp.ndarray, leaf: jnp.ndarray,
                      max_depth: int) -> jnp.ndarray:
    """Batch prediction over an ensemble on raw features — row-chunked via
    ``lax.map`` above ~1M rows so the per-step working set stays bounded
    regardless of N (the fused one-hot walk is cheap per block; very large
    single dispatches have crashed the worker on marginal links).
    feature/threshold/is_leaf: [Tr, T]; leaf: [Tr, T, V].
    Returns [N, Tr, V] leaf values (caller aggregates).

    TPU note: per-(row, tree) dynamic gathers (``take_along_axis``) lower to
    scalar gather loops and compile/run pathologically on TPU, so every node
    lookup is expressed as a one-hot contraction instead — the comparison
    one-hots fuse into the reductions, nothing of size [N, Tr, T] is
    materialized, and the MXU/VPU do the work (measured: ~100x faster compile
    AND faster steady-state than the gather form at 1Mx28, 20 trees)."""
    return _row_blocked(
        lambda xb: _predict_trees_block(xb, feature, threshold, is_leaf,
                                        leaf, max_depth), X)


def _row_blocked(per_block_fn, X: jnp.ndarray):
    """Apply ``per_block_fn`` over row blocks of ``X`` via ``lax.map`` when N
    exceeds the block size — the shared scaffold of the ensemble predictors
    (one traced body regardless of N; very large single dispatches have
    crashed the worker on marginal links)."""
    N = X.shape[0]
    BLOCK = 1 << 20
    if N <= BLOCK:
        return per_block_fn(X)
    n_blocks = -(-N // BLOCK)
    pad = n_blocks * BLOCK - N
    Xp = jnp.pad(X, ((0, pad), (0, 0))).reshape(n_blocks, BLOCK, X.shape[1])
    out = jax.lax.map(per_block_fn, Xp)
    return out.reshape((n_blocks * BLOCK,) + out.shape[2:])[:N]


@functools.partial(jax.jit, static_argnames=("max_depth", "members"))
def predict_trees_sum_grouped(X: jnp.ndarray, feature: jnp.ndarray,
                              threshold: jnp.ndarray, is_leaf: jnp.ndarray,
                              leaf: jnp.ndarray, max_depth: int,
                              members: int) -> jnp.ndarray:
    """Leaf SUMS for ``members`` tree ensembles at once → [N, members, V].

    The tree arrays are the members' stacks concatenated along the tree
    axis (equal trees-per-member).  One program replaces one predict
    dispatch per CV candidate; sums are rank-equivalent to each member's
    probability/margin (gini leaves sum to 1 per tree; GBT margins are a
    positive affine map of the leaf sum), which is all AUC metrics need."""
    T_total = feature.shape[0]
    per = T_total // members

    def blk(xb):
        lv = _predict_trees_block(xb, feature, threshold, is_leaf, leaf,
                                  max_depth)                 # [B, T, V]
        return lv.reshape(lv.shape[0], members, per,
                          lv.shape[-1]).sum(axis=2)          # [B, K, V]

    return _row_blocked(blk, X)


@functools.partial(jax.jit, static_argnames=("max_depth", "op"))
def predict_trees_agg(X: jnp.ndarray, feature: jnp.ndarray,
                      threshold: jnp.ndarray, is_leaf: jnp.ndarray,
                      leaf: jnp.ndarray, max_depth: int,
                      op: str = "mean") -> jnp.ndarray:
    """``predict_trees_raw`` with the tree axis reduced INSIDE each row
    block → [N, V].  The ensemble-score consumers only ever need the
    aggregate; materializing the full [N, Tr, V] leaf tensor costs
    Tr-times the HBM (≈1.8 GB at 11M x 20 trees x 2 classes) and is what
    pushed the near-capacity worker over during CV metric evaluation."""
    def blk(xb):
        lv = _predict_trees_block(xb, feature, threshold, is_leaf, leaf,
                                  max_depth)                   # [B, Tr, V]
        return lv.mean(axis=1) if op == "mean" else lv.sum(axis=1)

    return _row_blocked(blk, X)


def _predict_trees_block(X, feature, threshold, is_leaf, leaf,
                         max_depth: int):
    T = feature.shape[1]
    D = X.shape[1]
    dt = X.dtype
    k_iota = jnp.arange(T, dtype=jnp.int32)
    d_iota = jnp.arange(D, dtype=jnp.int32)
    feature_f = feature.astype(dt)
    # unvisited nodes carry +inf thresholds; 0 * inf = NaN would poison the
    # one-hot contraction.  The sentinel must ALSO survive summation: under
    # vmap the batched contraction can accumulate several sentinel lanes, and
    # float-max + float-max overflows to inf → NaN downstream (this silently
    # degraded every batched-CV GBT margin update).  1e30 keeps the compare
    # semantics (any real threshold is far smaller) with ~1e8 of headroom.
    threshold_f = jnp.where(jnp.isfinite(threshold),
                            threshold.astype(dt),
                            jnp.asarray(1e30, dt))
    leaf_flag = is_leaf.astype(dt)
    node = jnp.zeros((X.shape[0], feature.shape[0]), jnp.int32)

    def node_select(table, node):              # table [Tr, T] → [N, Tr]
        oh = (node[:, :, None] == k_iota).astype(dt)
        return jnp.einsum("ntk,tk->nt", oh, table)

    for _ in range(max_depth):
        f = node_select(feature_f, node).astype(jnp.int32)     # [N, Tr]
        th = node_select(threshold_f, node)
        lf = node_select(leaf_flag, node)
        f_oh = (f[:, :, None] == d_iota).astype(dt)            # fused
        xf = jnp.einsum("ntd,nd->nt", f_oh, X)
        nxt = 2 * node + 1 + (xf > th).astype(jnp.int32)
        nxt = jnp.where(nxt < T, nxt, node)    # bottom level has no children
        node = jnp.where(lf > 0.5, node, nxt)
    oh = (node[:, :, None] == k_iota).astype(dt)
    return jnp.einsum("ntk,tkv->ntv", oh, leaf.astype(dt))     # [N, Tr, V]


# --------------------------------------------------------------------------
# forest / boosting drivers
# --------------------------------------------------------------------------

@functools.lru_cache(maxsize=None)
def _forest_fitter(impurity: str, max_depth: int, n_bins: int, use_vmap: bool,
                   features_per_node: Optional[int] = None):
    """Jitted whole-forest fit, cached on the static tree shape so CV-grid
    candidates sharing a config reuse the compiled executable.  Feature
    subsetting is PER NODE (Spark featureSubsetStrategy semantics) via
    per-tree RNG keys."""

    def fn(B, splits, base_stats, boot, masks, keys, min_instances, min_gain,
           lam):
        def fit_one(args):
            bw, fm, k_ = args
            stats = base_stats * bw[:, None]
            return fit_tree(B, splits, stats, fm, impurity=impurity,
                            max_depth=max_depth, n_bins=n_bins,
                            min_instances=min_instances, min_gain=min_gain,
                            lam=lam, node_feature_key=k_,
                            features_per_node=features_per_node)

        # memory heuristic: deep trees → sequential lax.map, shallow → vmap
        if use_vmap:
            return jax.vmap(fit_one)((boot, masks, keys))
        return jax.lax.map(fit_one, (boot, masks, keys))

    return jax.jit(fn)


def _features_per_node(strategy: str, d: int) -> Optional[int]:
    """Per-node candidate count for a featureSubsetStrategy name; None = all."""
    if strategy == "all":
        return None
    k = {"sqrt": max(1, int(math.sqrt(d))),
         "onethird": max(1, d // 3)}.get(strategy)
    return None if k is None or k >= d else k


def fit_forest(X: np.ndarray, y: np.ndarray, *, task: str, n_classes: int,
               n_trees: int, max_depth: int, max_bins: int,
               min_instances: float, min_gain: float, subsample: float,
               feature_strategy: str, seed: int, bootstrap: bool = True,
               sample_weight: Optional[np.ndarray] = None) -> Dict[str, Any]:
    """Random forest: all trees in one vmapped XLA program (chunked via
    lax.map when deep trees would blow HBM)."""
    N, D = X.shape
    splits, B = shared_binned(X, max_bins)
    w0 = jnp.ones(N, jnp.float32) if sample_weight is None else jnp.asarray(sample_weight)
    yj = jnp.asarray(y, jnp.float32)
    key = jax.random.PRNGKey(seed)
    k_boot, k_feat = jax.random.split(key)
    boot = (jax.random.poisson(k_boot, subsample, (n_trees, N)).astype(jnp.float32)
            if bootstrap else jnp.ones((n_trees, N), jnp.float32))
    # features sample PER NODE inside fit_tree; the tree-level mask stays
    # all-true (per-TREE subsetting cannot learn interactions across subsets)
    masks = jnp.ones((n_trees, D)) > 0
    fpn = _features_per_node(feature_strategy, D) if n_trees > 1 else None
    tree_keys = jax.random.split(k_feat, n_trees)

    if task == "classification":
        impurity = "gini"
        yoh = jax.nn.one_hot(yj.astype(jnp.int32), n_classes, dtype=jnp.float32)
        base_stats = jnp.concatenate([jnp.ones((N, 1)), yoh], axis=1)
    else:
        impurity = "variance"
        base_stats = jnp.stack([jnp.ones(N), yj, yj * yj], axis=1)
    base_stats = base_stats * w0[:, None]

    # tree-vmap multiplies every per-row intermediate by n_trees; cap the
    # broadcast working set (~chunk * N * S * n_trees floats) at ~2 GiB
    S = base_stats.shape[1]
    est_bytes = 32 * N * max(S, 4) * 4 * n_trees
    use_vmap = max_depth <= 8 and n_trees <= 64 and est_bytes < 2 << 30
    fitter = _forest_fitter(impurity, max_depth, max_bins, use_vmap, fpn)
    fit_args = (B, jnp.asarray(splits), base_stats, boot, masks, tree_keys,
                jnp.float32(min_instances), jnp.float32(min_gain),
                jnp.float32(1.0))
    trees = fitter(*fit_args)
    from ..profiling import cost_analysis_enabled, record_program_cost
    if cost_analysis_enabled():
        record_program_cost("forest_fit", fitter, fit_args)
    return {"kind": "forest", "task": task, "n_classes": n_classes,
            "max_depth": max_depth,
            "feature": np.asarray(trees.feature),
            "threshold": np.asarray(trees.threshold),
            "is_leaf": np.asarray(trees.is_leaf),
            "leaf": np.asarray(trees.leaf),
            "feature_gain": np.asarray(trees.gain).sum(axis=0),
            "bin_splits": splits}


def gbt_round_body(B, splits, X, y, w0, margin, fmask, min_instances,
                   min_gain, lam, eta, *, task: str, max_depth: int,
                   n_bins: int, hist_dtype=None):
    """One second-order boosting round (grad/hess → tree fit → margin
    update) — the single source of the round math, shared by the local jitted
    fitter and the mesh-sharded variant in parallel/dist_fit.py."""
    if task == "classification":
        p = jax.nn.sigmoid(margin)
        g, h = p - y, jnp.maximum(p * (1 - p), 1e-6)
    else:
        g, h = margin - y, jnp.ones_like(margin)
    # weight ALL stat columns (incl. count) so zero-weight rows are fully
    # excluded from min_instances feasibility, matching the grid path
    stats = jnp.stack([jnp.ones_like(g), g, h], axis=1) * w0[:, None]
    tree = fit_tree(B, splits, stats, fmask, impurity="xgb",
                    max_depth=max_depth, n_bins=n_bins,
                    min_instances=min_instances, min_gain=min_gain, lam=lam,
                    hist_dtype=hist_dtype)
    pred = predict_trees_raw(X, tree.feature[None], tree.threshold[None],
                             tree.is_leaf[None], tree.leaf[None],
                             max_depth + 1)[:, 0, 0]
    return margin + eta * pred, tree


def fit_gbt(X: np.ndarray, y: np.ndarray, *, task: str, n_rounds: int,
            max_depth: int, max_bins: int, min_instances: float,
            min_gain: float, eta: float, lam: float, seed: int,
            min_child_weight: float = 0.0,
            sample_weight: Optional[np.ndarray] = None) -> Dict[str, Any]:
    """Gradient boosting (XGBoost-style second-order): Python loop over rounds
    around a jitted tree fit; grad/hess computed on device."""
    N, D = X.shape
    splits, B = shared_binned(X, max_bins)
    splits_j = jnp.asarray(splits)
    Xj = device_matrix(X)
    w0 = jnp.ones(N, jnp.float32) if sample_weight is None else jnp.asarray(sample_weight)
    yj = jnp.asarray(y, jnp.float32)
    fmask = jnp.ones((D,), jnp.float32) > 0
    base = jnp.float32(0.0) if task == "classification" else jnp.mean(yj)
    mi = max(float(min_instances), float(min_child_weight))
    # single-candidate run of the scanned grid fitter: all rounds in one
    # program, and the selector's final refit reuses the CV executable when
    # the fold shape matches
    chunk, batch_size = _tree_batch_budget(N, max_bins)
    fit_all = _gbt_grid_scan_fitter(task, max_depth, max_bins, chunk,
                                    batch_size, n_rounds)
    margins = jnp.full((1, N), base, jnp.float32)
    one = lambda v: jnp.asarray([v], jnp.float32)
    _, rounds = fit_all(B, splits_j, Xj, yj, margins, w0[None, :], fmask,
                        one(mi), one(min_gain), one(lam), one(eta))
    feature = np.asarray(rounds.feature[:, 0])
    threshold = np.asarray(rounds.threshold[:, 0])
    is_leaf = np.asarray(rounds.is_leaf[:, 0])
    leaf = np.asarray(rounds.leaf[:, 0])
    return {"kind": "gbt", "task": task, "n_classes": 2,
            "max_depth": max_depth, "eta": eta, "base": float(base),
            "feature": feature, "threshold": threshold,
            "is_leaf": is_leaf, "leaf": leaf,
            "feature_gain": np.asarray(rounds.gain[:, 0]).sum(axis=0),
            "bin_splits": splits}


# --------------------------------------------------------------------------
# batched (fold × grid) CV fitters — shared binned matrix, one dispatch per
# static config (≙ OpValidator.scala:320-349 thread-pool fan-out, SURVEY §2.6 P3)
# --------------------------------------------------------------------------

def _tree_batch_budget(N: int, n_bins: int) -> Tuple[int, int]:
    """(chunk, batch_size) so the one-hot working set of the trees running
    concurrently under ``lax.map(batch_size=...)`` fits the budget
    below (HBM minus data/program headroom).

    Measured on v5e at 1Mx28: wide feature chunks with a narrow tree batch
    (chunk=16, batch=4) run ~2.5x faster than narrow chunks with a wide batch
    (2, 8) — fewer scan iterations beat more vmap lanes, and XLA compile time
    is flat across the grid.  TRANSMOGRIFAI_TREE_BUDGET_GB overrides the
    histogram budget (smaller = safer on workers that hard-fault under
    sustained near-capacity HBM pressure at 10M+ rows)."""
    import os
    budget = int(float(os.environ.get(
        "TRANSMOGRIFAI_TREE_BUDGET_GB", 6)) * (1 << 30))
    per_col = max(2 * N, 1)       # bf16 bytes of one [N] column
    p_cols = 256                  # routing matrix P [N, P_n*S] upper bound
    # prefer 4 concurrent lanes at wide chunks; shrink chunk, then lanes
    for batch_size in (4, 2, 1):
        avail = budget // batch_size // per_col - p_cols
        chunk = min(16, avail // n_bins)
        if chunk >= 1:
            return int(chunk), batch_size
    return 1, 1


@functools.lru_cache(maxsize=None)
def _forest_grid_fitter(impurity: str, max_depth: int, n_bins: int,
                        bootstrap: bool, chunk: int, batch_size: int,
                        features_per_node: Optional[int] = None):
    """Jitted fit of ALL trees of a (fold × grid-point) forest group.

    Per-tree traced inputs: fold id (row-weight mask row), PRNG key (Poisson
    bootstrap drawn on device — no [Kt, N] boot matrix in HBM), min_instances,
    min_gain, subsample rate, feature mask.  ``lax.map(batch_size=...)`` bounds
    the histogram working set while still vmapping ``batch_size`` trees onto
    the MXU at once.  Feature subsetting is PER NODE (featureSubsetStrategy
    semantics) using a key derived from the tree's bootstrap key."""

    def fn(B, splits, base_stats, fold_w, fold_ids, keys, mis, mgs, subs,
           masks, lam):
        N = B.shape[0]

        def fit_one(args):
            fid, key, mi, mg, sub, fm = args
            k_boot, k_feat = jax.random.split(key)
            w = fold_w[fid]
            if bootstrap:
                bw = jax.random.poisson(k_boot, sub, (N,)).astype(jnp.float32) * w
            else:
                bw = w
            stats = base_stats * bw[:, None]
            return fit_tree(B, splits, stats, fm, impurity=impurity,
                            max_depth=max_depth, n_bins=n_bins,
                            min_instances=mi, min_gain=mg, lam=lam,
                            chunk=chunk, node_feature_key=k_feat,
                            features_per_node=features_per_node)

        return jax.lax.map(fit_one, (fold_ids, keys, mis, mgs, subs, masks),
                           batch_size=batch_size)

    return jax.jit(fn)


def _gbt_grid_round_body(B, splits, X, y, margins, weights, fmask, mis, mgs,
                         lams, etas, *, task, max_depth, n_bins, chunk,
                         batch_size):
    def one(args):
        margin, w, mi, mg, lam, eta = args
        if task == "classification":
            p = jax.nn.sigmoid(margin)
            g, h = p - y, jnp.maximum(p * (1 - p), 1e-6)
        else:
            g, h = margin - y, jnp.ones_like(margin)
        stats = jnp.stack([jnp.ones_like(g), g, h], axis=1) * w[:, None]
        tree = fit_tree(B, splits, stats, fmask, impurity="xgb",
                        max_depth=max_depth, n_bins=n_bins,
                        min_instances=mi, min_gain=mg, lam=lam, chunk=chunk)
        pred = predict_trees_raw(X, tree.feature[None], tree.threshold[None],
                                 tree.is_leaf[None], tree.leaf[None],
                                 max_depth + 1)[:, 0, 0]
        return margin + eta * pred, tree

    return jax.lax.map(one, (margins, weights, mis, mgs, lams, etas),
                       batch_size=batch_size)


@functools.lru_cache(maxsize=None)
def _gbt_grid_scan_fitter(task: str, max_depth: int, n_bins: int, chunk: int,
                          batch_size: int, n_rounds: int):
    """ALL boosting rounds of the whole (fold × grid-point) candidate block as
    ONE jitted program — ``lax.scan`` over rounds around the per-round
    ``lax.map`` over candidates.  One compile + one dispatch for the entire
    GBT family grid (the reference launches k·Σ|grid|·rounds Spark jobs).
    Returns (final margins [K, N], trees stacked [R, K, ...])."""

    def fn(B, splits, X, y, margins, weights, fmask, mis, mgs, lams, etas):
        def round_step(m, _):
            m2, trees = _gbt_grid_round_body(
                B, splits, X, y, m, weights, fmask, mis, mgs, lams, etas,
                task=task, max_depth=max_depth, n_bins=n_bins, chunk=chunk,
                batch_size=batch_size)
            return m2, trees

        return jax.lax.scan(round_step, margins, None, length=n_rounds)

    return jax.jit(fn)


# --------------------------------------------------------------------------
# prediction models + estimator stages
# --------------------------------------------------------------------------

def _predict_trees_np(X: np.ndarray, feature: np.ndarray, threshold: np.ndarray,
                      is_leaf: np.ndarray, leaf: np.ndarray,
                      max_depth: int) -> np.ndarray:
    """Numpy twin of ``predict_trees_raw`` — scoring is gather-bound host work;
    running it here avoids a fresh XLA compile per validation-slice shape in
    the CV loop.  Returns [N, Tr, V]."""
    N = X.shape[0]
    Tr = feature.shape[0]
    node = np.zeros((N, Tr), np.int32)
    ar = np.arange(Tr)[None, :]
    for _ in range(max_depth):
        f = feature[ar, node]
        th = threshold[ar, node]
        lf = is_leaf[ar, node]
        xf = np.take_along_axis(X, np.maximum(f, 0), axis=1)
        nxt = 2 * node + 1 + (xf > th).astype(np.int32)
        node = np.where(lf, node, nxt)
    return leaf[ar, node]


class TreeEnsembleModel(PredictionModel):
    def device_scores(self, Xd, full: bool = False) -> Dict[str, Any]:
        """Device-resident scoring: leaves are aggregated in HBM and only
        [N]/[N,C]-sized results exist afterwards — never transfer the
        [N, Tr, V] leaf tensor over the (slow) host link."""
        f = self.fitted
        args = (Xd, jnp.asarray(f["feature"]), jnp.asarray(f["threshold"]),
                jnp.asarray(f["is_leaf"]), jnp.asarray(f["leaf"]),
                int(f["max_depth"]) + 1)
        if f["kind"] == "forest":
            if f["task"] == "classification":
                prob = predict_trees_agg(*args, op="mean")     # [N, C]
                prob = prob / jnp.maximum(
                    jnp.sum(prob, axis=1, keepdims=True), 1e-12)
                out = {"prediction": jnp.argmax(prob, axis=1).astype(jnp.float32),
                       "probability": prob}
                if prob.shape[1] == 2:
                    out["scores"] = prob[:, 1]
                if full:
                    out["rawPrediction"] = jnp.log(jnp.maximum(prob, 1e-12))
                return out
            return {"prediction": predict_trees_agg(*args, op="mean")[:, 0]}
        margin = f["base"] + f["eta"] * predict_trees_agg(*args, op="sum")[:, 0]
        if f["task"] == "classification":
            p1 = jax.nn.sigmoid(margin)
            out = {"prediction": (p1 > 0.5).astype(jnp.float32),
                   "scores": p1, "margin": margin}
            if full:
                out["probability"] = jnp.stack([1.0 - p1, p1], axis=1)
                out["rawPrediction"] = jnp.stack([-margin, margin], axis=1)
            return out
        return {"prediction": margin}

    def predict_arrays(self, X: np.ndarray) -> Dict[str, np.ndarray]:
        f = self.fitted
        depth_iters = int(f["max_depth"]) + 1
        if isinstance(X, jax.Array) and _mxu_dtype() != jnp.float32:
            # X already lives on a real accelerator: score there and pull only
            # the per-row results
            out = self.device_scores(X)
            if f["kind"] == "forest" and f["task"] == "classification":
                prob = np.asarray(out["probability"])
                return {"prediction": np.asarray(out["prediction"]),
                        "probability": prob,
                        "rawPrediction": np.log(np.maximum(prob, 1e-12))}
            if f["kind"] == "gbt" and f["task"] == "classification":
                margin = np.asarray(out["margin"])
                p1 = np.asarray(out["scores"])
                return {"prediction": np.asarray(out["prediction"]),
                        "probability": np.stack([1 - p1, p1], axis=1),
                        "rawPrediction": np.stack([-margin, margin], axis=1)}
            return {"prediction": np.asarray(out["prediction"])}
        X32 = np.asarray(X, np.float32)
        leaves = _predict_trees_np(
            X32, np.asarray(f["feature"]), np.asarray(f["threshold"]),
            np.asarray(f["is_leaf"]), np.asarray(f["leaf"]), depth_iters)
        if f["kind"] == "forest":
            if f["task"] == "classification":
                prob = leaves.mean(axis=1)                     # [N, C]
                prob = prob / np.maximum(prob.sum(axis=1, keepdims=True), 1e-12)
                return {"prediction": np.argmax(prob, axis=1).astype(np.float32),
                        "probability": prob,
                        "rawPrediction": np.log(np.maximum(prob, 1e-12))}
            return {"prediction": leaves.mean(axis=1)[:, 0].astype(np.float32)}
        # gbt
        margin = f["base"] + f["eta"] * leaves[:, :, 0].sum(axis=1)
        if f["task"] == "classification":
            p1 = 1.0 / (1.0 + np.exp(-margin))
            prob = np.stack([1 - p1, p1], axis=1)
            return {"prediction": (p1 > 0.5).astype(np.float32),
                    "probability": prob,
                    "rawPrediction": np.stack([-margin, margin], axis=1)}
        return {"prediction": margin.astype(np.float32)}


class _ForestEstimatorBase(PredictorEstimator):
    model_cls = TreeEnsembleModel
    task = "classification"
    default_feature_strategy = "sqrt"
    hbm_heavy = True      # one-hot histogram working set ~6 GiB at large N
    # every tree statistic (node/histogram counts, leaf values, gains) is
    # sample-weighted and binning quantiles skip registered padding rows
    # (real_rows above), so zero-weight padded fits pick identical splits;
    # leaf values agree to float reduction order (the histogram chunk
    # budget is shape-dependent).  Bootstrap draws remain a valid
    # (weight-masked) sample at the padded shape.
    weighted_pad_exact = True
    supports_pretrace = True

    def __init__(self, num_trees: int = 20, max_depth: int = 5,
                 max_bins: int = MAX_BINS_DEFAULT, min_instances_per_node: int = 1,
                 min_info_gain: float = 0.0, subsampling_rate: float = 1.0,
                 feature_subset_strategy: str = "auto", seed: int = 42,
                 bootstrap: bool = True, **kw):
        super().__init__(num_trees=num_trees, max_depth=max_depth,
                         max_bins=max_bins,
                         min_instances_per_node=min_instances_per_node,
                         min_info_gain=min_info_gain,
                         subsampling_rate=subsampling_rate,
                         feature_subset_strategy=feature_subset_strategy,
                         seed=seed, bootstrap=bootstrap, **kw)

    def fit_arrays(self, X, y, sample_weight=None) -> Dict[str, Any]:
        strategy = self.get("feature_subset_strategy", "auto")
        if strategy == "auto":
            strategy = (self.default_feature_strategy
                        if self.get("num_trees", 20) > 1 else "all")
        from .linear import _n_classes
        n_classes = (_n_classes(y) if self.task == "classification" else 0)
        return fit_forest(
            X, y, task=self.task, n_classes=max(n_classes, 2),
            n_trees=int(self.get("num_trees", 20)),
            max_depth=int(self.get("max_depth", 5)),
            max_bins=int(self.get("max_bins", MAX_BINS_DEFAULT)),
            min_instances=float(self.get("min_instances_per_node", 1)),
            min_gain=float(self.get("min_info_gain", 0.0)),
            subsample=float(self.get("subsampling_rate", 1.0)),
            feature_strategy=strategy, seed=int(self.get("seed", 42)),
            bootstrap=bool(self.get("bootstrap", True)),
            sample_weight=sample_weight)


    def fit_arrays_grid(self, X, y, fold_weights, grids):
        """All (fold × grid-point × tree) fits of this candidate family share
        ONE binned matrix and dispatch once per static config — the reference
        re-bins and re-launches a Spark job per (fold, paramMap)
        (OpCrossValidation.scala:114-137).  Quantile split candidates are
        computed from the full matrix (label-free, standard CV practice)."""
        from collections import defaultdict
        K, G = fold_weights.shape[0], len(grids)
        out: list = [[None] * G for _ in range(K)]
        N, D = X.shape
        from .linear import _n_classes
        n_classes = (_n_classes(y) if self.task == "classification" else 0)
        n_classes = max(n_classes, 2)

        groups = defaultdict(list)
        for gi, p in enumerate(grids):
            m = {**self._params, **p}
            strategy = m.get("feature_subset_strategy", "auto")
            if strategy == "auto":
                strategy = (self.default_feature_strategy
                            if int(m.get("num_trees", 20)) > 1 else "all")
            groups[(int(m.get("num_trees", 20)), int(m.get("max_depth", 5)),
                    int(m.get("max_bins", MAX_BINS_DEFAULT)), strategy,
                    bool(m.get("bootstrap", True)),
                    int(m.get("seed", 42)))].append(gi)

        from ..aot import pretrace_mode
        pretrace = pretrace_mode()
        yj = jnp.asarray(y, jnp.float32)
        if self.task == "classification":
            impurity = "gini"
            if pretrace:
                # compile-only pass: an abstract aval for the big per-row
                # stats is enough to lower the fitter — skip materializing
                base_stats = jax.ShapeDtypeStruct((N, 1 + n_classes),
                                                  jnp.float32)
            else:
                yoh = jax.nn.one_hot(yj.astype(jnp.int32), n_classes,
                                     dtype=jnp.float32)
                base_stats = jnp.concatenate([jnp.ones((N, 1)), yoh], axis=1)
        else:
            impurity = "variance"
            base_stats = (jax.ShapeDtypeStruct((N, 3), jnp.float32)
                          if pretrace
                          else jnp.stack([jnp.ones(N), yj, yj * yj], axis=1))
        fold_w = to_device_f32(fold_weights, exact=True)
        splits_cache: dict = {}

        def mval(gi, name, default):
            return float({**self._params, **grids[gi]}.get(name, default))

        for (n_trees, max_depth, max_bins, strategy, bootstrap,
             seed), gidx in groups.items():
            if max_bins not in splits_cache:
                splits_cache[max_bins] = shared_binned(X, max_bins)
            splits, B = splits_cache[max_bins]
            Gg = len(gidx)
            Kt = K * Gg * n_trees
            # (split kept for draw-compatibility with fit_forest's seeding;
            # per-node feature keys derive from each tree's bootstrap key)
            k_boot, _ = jax.random.split(jax.random.PRNGKey(seed))
            # per-NODE feature subsetting happens inside fit_tree (keys drawn
            # from each tree's key); the tree-level mask stays all-true
            fpn = (_features_per_node(strategy, D) if n_trees > 1 else None)
            masks = jnp.ones((Kt, D)) > 0
            # one bootstrap key per TREE INDEX, shared across folds and grid
            # points — grid points differing only in traced params see
            # identical draws (candidates are ranked by hyper-parameters, not
            # bootstrap noise), mirroring fit_forest's fixed-seed draws
            keys_one = jax.random.split(k_boot, n_trees)
            keys = jax.random.wrap_key_data(
                jnp.tile(jax.random.key_data(keys_one), (K * Gg, 1)))
            fold_ids = jnp.asarray(
                np.repeat(np.arange(K, dtype=np.int32), Gg * n_trees))
            per_tree = lambda vals: jnp.asarray(
                np.tile(np.repeat(np.asarray(vals, np.float32), n_trees), K))
            mis = per_tree([mval(gi, "min_instances_per_node", 1) for gi in gidx])
            mgs = per_tree([mval(gi, "min_info_gain", 0.0) for gi in gidx])
            subs = per_tree([mval(gi, "subsampling_rate", 1.0) for gi in gidx])
            chunk, batch_size = _tree_batch_budget(N, max_bins)
            fitter = _forest_grid_fitter(impurity, max_depth, max_bins,
                                         bootstrap, chunk, batch_size, fpn)
            grid_args = (B, jnp.asarray(splits), base_stats, fold_w,
                         fold_ids, keys, mis, mgs, subs, masks,
                         jnp.float32(1.0))
            from ..aot_registry import grid_call, grid_compile
            f_statics = dict(impurity=impurity, maxDepth=max_depth,
                             maxBins=max_bins, bootstrap=bootstrap,
                             chunk=chunk, batchSize=batch_size, fpn=fpn)
            if pretrace:
                # registry hit → the executable deserializes now and the
                # sweep's real fit dispatches it (zero compiles); miss →
                # lower+compile into the persistent compile cache (and
                # _SHARED_BINS, above) and publish the fresh build
                grid_compile("trees.forest_grid_fit", fitter, grid_args,
                             sig_statics=f_statics)
                continue
            trees = grid_call("trees.forest_grid_fit", fitter, grid_args,
                              sig_statics=f_statics)
            from ..profiling import cost_analysis_enabled, record_program_cost
            if cost_analysis_enabled():
                record_program_cost("forest_grid_fit", fitter, grid_args)
            # keep the tree arrays device-resident: candidates slice views of
            # the [Kt, ...] stacks; they only cross the host link if a model
            # is serialized or scored on host data
            feature = trees.feature
            threshold = trees.threshold
            is_leaf = trees.is_leaf
            leaf = trees.leaf
            for k in range(K):
                for j, gi in enumerate(gidx):
                    s = (k * Gg + j) * n_trees
                    out[k][gi] = {
                        "kind": "forest", "task": self.task,
                        "n_classes": n_classes, "max_depth": max_depth,
                        "feature": feature[s:s + n_trees],
                        "threshold": threshold[s:s + n_trees],
                        "is_leaf": is_leaf[s:s + n_trees],
                        "leaf": leaf[s:s + n_trees],
                        "feature_gain": trees.gain[s:s + n_trees].sum(axis=0),
                        "bin_splits": splits}
        return out


class OpRandomForestClassifier(_ForestEstimatorBase):
    """≙ OpRandomForestClassifier.scala:58."""
    task = "classification"
    default_feature_strategy = "sqrt"


class OpRandomForestRegressor(_ForestEstimatorBase):
    """≙ OpRandomForestRegressor."""
    task = "regression"
    default_feature_strategy = "onethird"


class OpDecisionTreeClassifier(_ForestEstimatorBase):
    """≙ OpDecisionTreeClassifier: a single deterministic tree — no
    bootstrap, all features (like Spark's DecisionTreeClassifier)."""
    task = "classification"

    def __init__(self, max_depth: int = 5, **kw):
        kw.setdefault("num_trees", 1)
        kw.setdefault("feature_subset_strategy", "all")
        kw.setdefault("subsampling_rate", 1.0)
        kw.setdefault("bootstrap", False)
        super().__init__(max_depth=max_depth, **kw)


class OpDecisionTreeRegressor(OpDecisionTreeClassifier):
    task = "regression"


class _GBTEstimatorBase(PredictorEstimator):
    model_cls = TreeEnsembleModel
    task = "classification"
    hbm_heavy = True
    # GBT fits are deterministic (no per-fit RNG) and fully sample-weighted:
    # zero-weight padded rows have zero grad/hess and padding-aware binning
    # (real_rows) keeps split points fixed — padded fits choose identical
    # trees, with leaf values equal to float reduction order
    weighted_pad_exact = True
    supports_pretrace = True

    def __init__(self, max_iter: int = 20, max_depth: int = 5,
                 max_bins: int = MAX_BINS_DEFAULT, min_instances_per_node: int = 1,
                 min_info_gain: float = 0.0, step_size: float = 0.1,
                 reg_lambda: float = 1.0, seed: int = 42, **kw):
        super().__init__(max_iter=max_iter, max_depth=max_depth, max_bins=max_bins,
                         min_instances_per_node=min_instances_per_node,
                         min_info_gain=min_info_gain, step_size=step_size,
                         reg_lambda=reg_lambda, seed=seed, **kw)

    def fit_arrays(self, X, y, sample_weight=None) -> Dict[str, Any]:
        return fit_gbt(
            X, y, task=self.task,
            n_rounds=int(self.get("max_iter", 20)),
            max_depth=int(self.get("max_depth", 5)),
            max_bins=int(self.get("max_bins", MAX_BINS_DEFAULT)),
            min_instances=float(self.get("min_instances_per_node", 1)),
            min_gain=float(self.get("min_info_gain", 0.0)),
            eta=float(self.get("step_size", 0.1)),
            lam=float(self.get("reg_lambda", 1.0)),
            min_child_weight=float(self.get("min_child_weight", 0.0)),
            seed=int(self.get("seed", 42)), sample_weight=sample_weight)


    def fit_arrays_grid(self, X, y, fold_weights, grids):
        """Batched GBT grid: one jitted dispatch per boosting round fits that
        round's tree for ALL (fold × grid-point) candidates at once over a
        shared binned matrix (margins/weights [K·G, N] in HBM)."""
        from collections import defaultdict
        K, G = fold_weights.shape[0], len(grids)
        out: list = [[None] * G for _ in range(K)]
        N, D = X.shape

        groups = defaultdict(list)
        for gi, p in enumerate(grids):
            m = {**self._params, **p}
            groups[(int(m.get("max_iter", 20)), int(m.get("max_depth", 5)),
                    int(m.get("max_bins", MAX_BINS_DEFAULT)))].append(gi)

        Xj = device_matrix(X)
        yj = jnp.asarray(y, jnp.float32)
        fold_w = to_device_f32(fold_weights, exact=True)
        fmask = jnp.ones((D,), jnp.float32) > 0
        splits_cache: dict = {}

        def mval(gi, name, default):
            return float({**self._params, **grids[gi]}.get(name, default))

        for (n_rounds, max_depth, max_bins), gidx in groups.items():
            if max_bins not in splits_cache:
                splits_cache[max_bins] = shared_binned(X, max_bins)
            splits, B = splits_cache[max_bins]
            Gg = len(gidx)
            Kc = K * Gg
            from ..aot import pretrace_mode
            pretrace = pretrace_mode()
            if pretrace:
                # compile-only pass: abstract avals for the [Kc, N] buffers
                W = jax.ShapeDtypeStruct((Kc, N), jnp.float32)
                margins = jax.ShapeDtypeStruct((Kc, N), jnp.float32)
            else:
                # candidate kc = k*Gg + j
                W = jnp.repeat(fold_w, Gg, axis=0)             # [Kc, N]
                if self.task == "classification":
                    base = jnp.zeros((Kc,), jnp.float32)
                else:
                    base = (fold_w @ yj) / jnp.maximum(
                        jnp.sum(fold_w, axis=1), 1e-12)        # [K]
                    base = jnp.repeat(base, Gg)
                margins = jnp.broadcast_to(
                    base[:, None], (Kc, N)).astype(jnp.float32)
            per_cand = lambda vals: np.tile(np.asarray(vals, np.float32), K)
            mis = per_cand([max(mval(gi, "min_instances_per_node", 1),
                                mval(gi, "min_child_weight", 0.0))
                            for gi in gidx])
            mgs = per_cand([mval(gi, "min_info_gain", 0.0) for gi in gidx])
            lams = per_cand([mval(gi, "reg_lambda", 1.0) for gi in gidx])
            etas = per_cand([mval(gi, "step_size", 0.1) for gi in gidx])
            chunk, batch_size = _tree_batch_budget(N, max_bins)
            fit_all = _gbt_grid_scan_fitter(self.task, max_depth, max_bins,
                                            chunk, batch_size, n_rounds)
            mis_d, mgs_d, lams_d, etas_d = (jnp.asarray(a) for a in
                                            (mis, mgs, lams, etas))
            gbt_args = (B, jnp.asarray(splits), Xj, yj, margins, W, fmask,
                        mis_d, mgs_d, lams_d, etas_d)
            from ..aot_registry import grid_call, grid_compile
            g_statics = dict(task=self.task, maxDepth=max_depth,
                             maxBins=max_bins, chunk=chunk,
                             batchSize=batch_size, rounds=n_rounds)
            if pretrace:
                grid_compile("trees.gbt_grid_fit", fit_all, gbt_args,
                             sig_statics=g_statics)
                continue
            margins, rounds = grid_call("trees.gbt_grid_fit", fit_all,
                                        gbt_args, sig_statics=g_statics)
            from ..profiling import cost_analysis_enabled, record_program_cost
            if cost_analysis_enabled():
                record_program_cost("gbt_grid_fit", fit_all, gbt_args)
            # device-resident [Kc, R, T] stacks; sliced per candidate below
            feature = jnp.swapaxes(rounds.feature, 0, 1)
            threshold = jnp.swapaxes(rounds.threshold, 0, 1)
            is_leaf = jnp.swapaxes(rounds.is_leaf, 0, 1)
            leaf = jnp.swapaxes(rounds.leaf, 0, 1)
            base_np = np.asarray(base)
            for k in range(K):
                for j, gi in enumerate(gidx):
                    kc = k * Gg + j
                    out[k][gi] = {
                        "kind": "gbt", "task": self.task, "n_classes": 2,
                        "max_depth": max_depth,
                        "eta": float(etas[kc]), "base": float(base_np[kc]),
                        "feature": feature[kc], "threshold": threshold[kc],
                        "is_leaf": is_leaf[kc], "leaf": leaf[kc],
                        "feature_gain": rounds.gain[:, kc].sum(axis=0),
                        "bin_splits": splits}
        return out


class OpGBTClassifier(_GBTEstimatorBase):
    """≙ OpGBTClassifier (binary only, like Spark's GBTClassifier)."""
    task = "classification"


class OpGBTRegressor(_GBTEstimatorBase):
    """≙ OpGBTRegressor."""
    task = "regression"


class OpXGBoostClassifier(_GBTEstimatorBase):
    """≙ OpXGBoostClassifier.scala:47 — same boosted-tree engine with XGBoost
    parameter names/defaults (eta, numRound, minChildWeight, lambda)."""
    task = "classification"

    def __init__(self, num_round: int = 100, eta: float = 0.3,
                 max_depth: int = 6, min_child_weight: float = 1.0,
                 reg_lambda: float = 1.0, seed: int = 42, **kw):
        super().__init__(max_iter=num_round, max_depth=max_depth,
                         step_size=eta, reg_lambda=reg_lambda, seed=seed,
                         min_child_weight=min_child_weight, **kw)


class OpXGBoostRegressor(OpXGBoostClassifier):
    task = "regression"
