"""Linear-family models — the TPU-native re-design of the reference's Spark
MLlib wrappers (core/.../stages/impl/classification/OpLogisticRegression.scala:46,
OpLinearSVC.scala, OpNaiveBayes.scala, OpMultilayerPerceptronClassifier.scala,
core/.../impl/regression/OpLinearRegression.scala,
OpGeneralizedLinearRegression.scala).

Each estimator's hyper-parameters mirror the Spark ML params that the
reference's DefaultSelectorParams grids sweep (DefaultSelectorParams.scala:36-68).
The fits are single fused XLA programs (see models/solvers.py).
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..columns import device_matrix, to_device_f32
from ..sparse.matrix import SparseMatrix
from .base import PredictionModel, PredictorEstimator
from .solvers import (FitResult, fista_fit, linear_grid_fit, naive_bayes_fit,
                      ridge_fit, ridge_grid_fit, sparse_fista_fit,
                      sparse_linear_grid_fit, standardize, unscale_params)


def _n_classes(y) -> int:
    if not len(y):
        return 2
    import jax
    if isinstance(y, jax.Array):
        # reduce on device: np.max on a device array round-trips the whole
        # column over the (slow) accelerator link — measured 16s at 1M rows
        # on the tunneled TPU vs one d2h scalar here
        return int(jnp.max(y)) + 1
    return int(np.max(y)) + 1


def _grouped_grid_fit(est, X, y, fold_weights, grids, *, loss: str,
                      n_classes: int, l2l1, fitted_extra: Dict[str, Any]):
    """Shared (fold × grid) batched fit for the linear family: grid points are
    grouped by their static config (max_iter/intercept/standardization/tol)
    and each group trains as one nested-vmap XLA program over
    (fold_weights [F,N]) × (l2s, l1s [G]).  Returns fitted dicts [F][G]."""
    from collections import defaultdict
    K, G = fold_weights.shape[0], len(grids)
    out: list = [[None] * G for _ in range(K)]
    groups = defaultdict(list)
    for gi, p in enumerate(grids):
        m = {**est._params, **p}
        groups[(int(m.get("max_iter", 100)), bool(m.get("fit_intercept", True)),
                bool(m.get("standardization", True)),
                float(m.get("tol", 1e-6)))].append(gi)
    sparse = isinstance(X, SparseMatrix)
    Xj = X if sparse else device_matrix(X)
    yj = jnp.asarray(y, jnp.float32)
    Wj = to_device_f32(fold_weights, exact=True)
    nc = 1 if n_classes <= 2 else n_classes
    for (max_iter, fit_intercept, standardization, tol), gidx in groups.items():
        pens = [l2l1({**est._params, **grids[gi]}) for gi in gidx]
        l2s = jnp.asarray([p[0] for p in pens], jnp.float32)
        l1s = jnp.asarray([p[1] for p in pens], jnp.float32)
        if not sparse:
            # mesh sweeps with a 'model' axis wider than 1: lay the penalty
            # grid out over that axis (candidate_sharding) instead of
            # replicating it, so each model-column of devices solves its own
            # slice of the grid (SURVEY §2.6 P3) — the mesh rides in on X's
            # sharding, no extra fit-signature plumbing
            from ..parallel.mesh import candidate_mesh_for, candidate_sharding
            cmesh = candidate_mesh_for(Xj, len(gidx))
            if cmesh is not None:
                import jax as _jax
                csh = candidate_sharding(cmesh)
                l2s = _jax.device_put(l2s, csh)
                l1s = _jax.device_put(l1s, csh)
        # all grid dispatch goes through the registry seam (aot_registry):
        # a registry hit runs an installed executable with zero traces and
        # zero compiles; a miss runs the ordinary jit call and publishes a
        # fresh build for the rest of the fleet
        from ..aot import pretrace_mode
        from ..aot_registry import grid_call, grid_compile
        if sparse:
            label = "linear.sparse_grid_fit"
            g_fn = sparse_linear_grid_fit
            g_args = (Xj.values, Xj.indices, Xj.row_ids, yj, Wj, l2s, l1s)
            g_statics = dict(n_rows=Xj.n_rows, n_cols=Xj.n_cols, loss=loss,
                             fit_intercept=fit_intercept,
                             standardization=standardization,
                             max_iter=max_iter, tol=tol, n_classes=nc)
        elif loss == "squared" and all(p[1] == 0.0 for p in pens):
            label = "linear.ridge_grid_fit"
            g_fn = ridge_grid_fit
            g_args = (Xj, yj, Wj, l2s)
            g_statics = dict(fit_intercept=fit_intercept,
                             standardization=standardization)
        else:
            label = "linear.grid_fit"
            g_fn = linear_grid_fit
            g_args = (Xj, yj, Wj, l2s, l1s)
            g_statics = dict(loss=loss, fit_intercept=fit_intercept,
                             standardization=standardization,
                             max_iter=max_iter, tol=tol, n_classes=nc)
        if pretrace_mode():
            # background pre-trace: registry hit → deserialize the
            # executable now (the real fit below dispatches it directly);
            # miss → lower+compile into the persistent cache and publish
            grid_compile(label, g_fn, g_args, static_kwargs=g_statics)
            continue
        from ..profiling import cost_analysis_enabled, record_program_cost
        res = grid_call(label, g_fn, g_args, static_kwargs=g_statics)
        if cost_analysis_enabled() and not sparse:
            record_program_cost(label, g_fn, g_args, g_statics)
        coef = np.asarray(res.coef)
        inter = np.asarray(res.intercept)
        n_it = np.asarray(res.n_iter)
        for j, gi in enumerate(gidx):
            for k in range(K):
                out[k][gi] = {"coef": coef[k, j], "intercept": inter[k, j],
                              "n_iter": int(n_it[k, j]), **fitted_extra}
    return out


def _np_sigmoid(z: np.ndarray) -> np.ndarray:
    return 1.0 / (1.0 + np.exp(-np.clip(z, -60.0, 60.0)))


def _np_softmax(z: np.ndarray) -> np.ndarray:
    z = z - np.max(z, axis=-1, keepdims=True)
    e = np.exp(z)
    return e / np.sum(e, axis=-1, keepdims=True)


def _binary_outputs(margin: np.ndarray) -> Dict[str, np.ndarray]:
    """Prediction triple from binary margins.  Pure numpy on purpose: scoring
    is elementwise host work; eager JAX dispatch here costs device round-trips
    per CV candidate (the fits are the device programs, not this)."""
    margin = np.asarray(margin, dtype=np.float32)
    p1 = _np_sigmoid(margin)
    prob = np.stack([1.0 - p1, p1], axis=1)
    raw = np.stack([-margin, margin], axis=1)
    return {"prediction": (p1 > 0.5).astype(np.float32),
            "probability": prob, "rawPrediction": raw}


@functools.partial(jax.jit, static_argnames=("kind", "full", "family"))
def _linear_device_scores(Xd, coef, intercept, *, kind: str, full: bool,
                          family: str = "gaussian"):
    """One fused program for the whole device-score chain — the eager
    version dispatched 4-7 separate tiny executables (matmul, sigmoid,
    greater, stack, ...) per call, each paying dispatch latency (and a
    first-time executable load) on the tunneled TPU."""
    return _scores_from_linear(Xd @ coef, intercept, kind=kind, full=full,
                               family=family)


@functools.partial(jax.jit, static_argnames=("kind", "full", "family"))
def _scores_from_linear(lin, intercept, *, kind: str, full: bool,
                        family: str = "gaussian"):
    """Score-chain tail given the linear predictor ``lin = X @ coef`` — the
    shared seam that lets the sparse path swap in a segment-sum matvec
    while keeping the post-processing program identical to the dense one."""
    if kind == "multinomial":
        logits = lin + intercept
        out = {"prediction": jnp.argmax(logits, axis=1).astype(jnp.float32),
               "probability": jax.nn.softmax(logits, axis=-1)}
        if full:
            out["rawPrediction"] = logits
        return out
    margin = lin + (intercept[0] if intercept.ndim else intercept)
    if kind == "binary":
        p1 = jax.nn.sigmoid(margin)
        out = {"prediction": (margin > 0).astype(jnp.float32), "scores": p1}
        if full:
            out["probability"] = jnp.stack([1.0 - p1, p1], axis=1)
            out["rawPrediction"] = jnp.stack([-margin, margin], axis=1)
        return out
    if kind == "svc":
        out = {"prediction": (margin > 0).astype(jnp.float32),
               "scores": margin}
        if full:
            out["rawPrediction"] = jnp.stack([-margin, margin], axis=1)
        return out
    if kind == "glm":
        eta = jnp.clip(margin, -30.0, 30.0)
        pred = {"poisson": jnp.exp, "gamma": jnp.exp,
                "binomial": jax.nn.sigmoid,
                "gaussian": lambda e: e}[family](eta)
        return {"prediction": pred}
    return {"prediction": margin}


class LinearPredictionModel(PredictionModel):
    """Fitted linear model.  ``fitted``: coef [D] or [D,C], intercept,
    kind ∈ {binary, multinomial, regression, svc}."""

    def device_scores(self, Xd, full: bool = False) -> Dict[str, Any]:
        """Device-resident scoring: returns small per-row device arrays so
        only scalars/metric results ever cross the (slow) host link.  The CV
        loop uses the minimal set ({'prediction', 'scores'|'probability'});
        ``full=True`` mirrors ``predict_arrays``' key set exactly (probability
        + rawPrediction) so the Prediction schema is residency-independent."""
        kind = self.fitted["kind"]
        if isinstance(Xd, SparseMatrix):
            # margin via segment-sum matvec; identical post-processing
            return _scores_from_linear(
                Xd @ jnp.asarray(self.fitted["coef"]),
                jnp.asarray(self.fitted["intercept"]), kind=kind,
                full=bool(full), family=self.fitted.get("family", "gaussian"))
        return _linear_device_scores(
            Xd, jnp.asarray(self.fitted["coef"]),
            jnp.asarray(self.fitted["intercept"]), kind=kind,
            full=bool(full), family=self.fitted.get("family", "gaussian"))

    def predict_arrays(self, X) -> Dict[str, np.ndarray]:
        coef = np.asarray(self.fitted["coef"], dtype=np.float32)
        intercept = np.asarray(self.fitted["intercept"], dtype=np.float32)
        kind = self.fitted["kind"]
        lin = np.asarray(X @ coef) if isinstance(X, SparseMatrix) else X @ coef
        if kind == "multinomial":
            logits = lin + intercept
            prob = _np_softmax(logits)
            return {"prediction": np.argmax(logits, axis=1).astype(np.float32),
                    "probability": prob, "rawPrediction": logits}
        margin = lin + (intercept[0] if intercept.ndim else intercept)
        if kind == "binary":
            return _binary_outputs(margin)
        if kind == "svc":
            raw = np.stack([-margin, margin], axis=1)
            return {"prediction": (margin > 0).astype(np.float32),
                    "probability": None, "rawPrediction": raw}
        return {"prediction": margin.astype(np.float32)}


class OpLogisticRegression(PredictorEstimator):
    """≙ OpLogisticRegression (elastic-net logistic; binary or multinomial)."""

    model_cls = LinearPredictionModel
    # every reduction in the solvers is sample-weighted (sum(w·)/sum(w)), so
    # zero-weight padding rows leave the fit exact — lets the sweep pad N up
    # a ladder to reuse compiled executables across nearby dataset sizes
    weighted_pad_exact = True
    supports_pretrace = True

    def __init__(self, reg_param: float = 0.0, elastic_net_param: float = 0.0,
                 max_iter: int = 100, tol: float = 1e-6,
                 fit_intercept: bool = True, standardization: bool = True, **kw):
        super().__init__(reg_param=reg_param, elastic_net_param=elastic_net_param,
                         max_iter=max_iter, tol=tol, fit_intercept=fit_intercept,
                         standardization=standardization, **kw)

    def fit_arrays(self, X, y, sample_weight=None) -> Dict[str, Any]:
        n, d = X.shape
        w = jnp.ones(n, jnp.float32) if sample_weight is None else jnp.asarray(sample_weight)
        C = _n_classes(y)
        reg = float(self.get("reg_param", 0.0))
        en = float(self.get("elastic_net_param", 0.0))
        l1, l2 = reg * en, reg * (1.0 - en)
        loss = "logistic" if C <= 2 else "softmax"
        nc = 1 if C <= 2 else C
        if isinstance(X, SparseMatrix):
            res = sparse_fista_fit(
                X, jnp.asarray(y), w, l2, l1, loss=loss,
                fit_intercept=self.get("fit_intercept", True),
                standardization=self.get("standardization", True),
                max_iter=int(self.get("max_iter", 100)),
                tol=float(self.get("tol", 1e-6)), n_classes=nc)
            return {"coef": np.asarray(res.coef),
                    "intercept": np.asarray(res.intercept),
                    "kind": "binary" if C <= 2 else "multinomial",
                    "n_classes": C, "n_iter": int(res.n_iter)}
        Xj = jnp.asarray(X)
        if self.get("standardization", True):
            Xs, mean, scale = standardize(Xj, w, center=self.get("fit_intercept", True))
        else:
            Xs, mean, scale = Xj, jnp.zeros(d), jnp.ones(d)
        res = fista_fit(Xs, jnp.asarray(y), w, jnp.float32(l2), jnp.float32(l1),
                        loss=loss, fit_intercept=self.get("fit_intercept", True),
                        max_iter=int(self.get("max_iter", 100)),
                        tol=float(self.get("tol", 1e-6)), n_classes=nc)
        res = unscale_params(res, mean, scale, nc)
        return {"coef": np.asarray(res.coef), "intercept": np.asarray(res.intercept),
                "kind": "binary" if C <= 2 else "multinomial",
                "n_classes": C, "n_iter": int(res.n_iter)}

    def fit_arrays_grid(self, X, y, fold_weights, grids):
        C = _n_classes(y)

        def l2l1(m):
            reg = float(m.get("reg_param", 0.0))
            en = float(m.get("elastic_net_param", 0.0))
            return reg * (1.0 - en), reg * en

        return _grouped_grid_fit(
            self, X, y, fold_weights, grids,
            loss="logistic" if C <= 2 else "softmax", n_classes=C, l2l1=l2l1,
            fitted_extra={"kind": "binary" if C <= 2 else "multinomial",
                          "n_classes": C})


class OpLinearSVC(PredictorEstimator):
    """≙ OpLinearSVC (squared-hinge linear SVM; binary, no probabilities)."""

    model_cls = LinearPredictionModel
    weighted_pad_exact = True   # see OpLogisticRegression
    supports_pretrace = True

    def __init__(self, reg_param: float = 0.0, max_iter: int = 100,
                 tol: float = 1e-6, fit_intercept: bool = True,
                 standardization: bool = True, **kw):
        super().__init__(reg_param=reg_param, max_iter=max_iter, tol=tol,
                         fit_intercept=fit_intercept, standardization=standardization, **kw)

    def fit_arrays(self, X, y, sample_weight=None) -> Dict[str, Any]:
        n, d = X.shape
        w = jnp.ones(n, jnp.float32) if sample_weight is None else jnp.asarray(sample_weight)
        if isinstance(X, SparseMatrix):
            res = sparse_fista_fit(
                X, jnp.asarray(y), w, float(self.get("reg_param", 0.0)), 0.0,
                loss="squared_hinge",
                fit_intercept=self.get("fit_intercept", True),
                standardization=self.get("standardization", True),
                max_iter=int(self.get("max_iter", 100)),
                tol=float(self.get("tol", 1e-6)))
            return {"coef": np.asarray(res.coef),
                    "intercept": np.asarray(res.intercept),
                    "kind": "svc", "n_classes": 2, "n_iter": int(res.n_iter)}
        Xj = jnp.asarray(X)
        if self.get("standardization", True):
            Xs, mean, scale = standardize(Xj, w, center=self.get("fit_intercept", True))
        else:
            Xs, mean, scale = Xj, jnp.zeros(d), jnp.ones(d)
        res = fista_fit(Xs, jnp.asarray(y), w,
                        jnp.float32(self.get("reg_param", 0.0)), jnp.float32(0.0),
                        loss="squared_hinge",
                        fit_intercept=self.get("fit_intercept", True),
                        max_iter=int(self.get("max_iter", 100)),
                        tol=float(self.get("tol", 1e-6)))
        res = unscale_params(res, mean, scale, 1)
        return {"coef": np.asarray(res.coef), "intercept": np.asarray(res.intercept),
                "kind": "svc", "n_classes": 2, "n_iter": int(res.n_iter)}

    def fit_arrays_grid(self, X, y, fold_weights, grids):
        return _grouped_grid_fit(
            self, X, y, fold_weights, grids, loss="squared_hinge", n_classes=2,
            l2l1=lambda m: (float(m.get("reg_param", 0.0)), 0.0),
            fitted_extra={"kind": "svc", "n_classes": 2})


class OpLinearRegression(PredictorEstimator):
    """≙ OpLinearRegression (elastic-net least squares; closed-form ridge when
    l1 = 0)."""

    model_cls = LinearPredictionModel
    weighted_pad_exact = True   # see OpLogisticRegression
    supports_pretrace = True

    def __init__(self, reg_param: float = 0.0, elastic_net_param: float = 0.0,
                 max_iter: int = 100, tol: float = 1e-6,
                 fit_intercept: bool = True, standardization: bool = True, **kw):
        super().__init__(reg_param=reg_param, elastic_net_param=elastic_net_param,
                         max_iter=max_iter, tol=tol, fit_intercept=fit_intercept,
                         standardization=standardization, **kw)

    def fit_arrays(self, X, y, sample_weight=None) -> Dict[str, Any]:
        n, d = X.shape
        w = jnp.ones(n, jnp.float32) if sample_weight is None else jnp.asarray(sample_weight)
        reg = float(self.get("reg_param", 0.0))
        en = float(self.get("elastic_net_param", 0.0))
        l1, l2 = reg * en, reg * (1.0 - en)
        if isinstance(X, SparseMatrix):
            res = sparse_fista_fit(
                X, jnp.asarray(y), w, l2, l1, loss="squared",
                fit_intercept=self.get("fit_intercept", True),
                standardization=self.get("standardization", True),
                max_iter=int(self.get("max_iter", 100)),
                tol=float(self.get("tol", 1e-6)))
            return {"coef": np.asarray(res.coef),
                    "intercept": np.asarray(res.intercept),
                    "kind": "regression", "n_iter": int(res.n_iter)}
        Xj, yj = jnp.asarray(X), jnp.asarray(y)
        if self.get("standardization", True):
            Xs, mean, scale = standardize(Xj, w, center=self.get("fit_intercept", True))
        else:
            Xs, mean, scale = Xj, jnp.zeros(d), jnp.ones(d)
        if l1 == 0.0:
            res = ridge_fit(Xs, yj, w, jnp.float32(l2),
                            fit_intercept=self.get("fit_intercept", True))
        else:
            res = fista_fit(Xs, yj, w, jnp.float32(l2), jnp.float32(l1),
                            loss="squared",
                            fit_intercept=self.get("fit_intercept", True),
                            max_iter=int(self.get("max_iter", 100)),
                            tol=float(self.get("tol", 1e-6)))
        res = unscale_params(res, mean, scale, 1)
        return {"coef": np.asarray(res.coef), "intercept": np.asarray(res.intercept),
                "kind": "regression", "n_iter": int(res.n_iter)}

    def fit_arrays_grid(self, X, y, fold_weights, grids):
        def l2l1(m):
            reg = float(m.get("reg_param", 0.0))
            en = float(m.get("elastic_net_param", 0.0))
            return reg * (1.0 - en), reg * en

        return _grouped_grid_fit(
            self, X, y, fold_weights, grids, loss="squared", n_classes=2,
            l2l1=l2l1, fitted_extra={"kind": "regression"})


class OpGeneralizedLinearRegression(PredictorEstimator):
    """≙ OpGeneralizedLinearRegression: families gaussian/binomial/poisson/gamma
    (log/identity/logit links as in the reference grid
    BinaryClassificationModelSelector.scala / DefaultSelectorParams.scala:56-65)."""

    weighted_pad_exact = True   # see OpLogisticRegression
    supports_pretrace = True

    def __init__(self, family: str = "gaussian", link: Optional[str] = None,
                 reg_param: float = 0.0, max_iter: int = 50, tol: float = 1e-6,
                 fit_intercept: bool = True, **kw):
        super().__init__(family=family, link=link, reg_param=reg_param,
                         max_iter=max_iter, tol=tol, fit_intercept=fit_intercept, **kw)

    def fit_arrays(self, X, y, sample_weight=None) -> Dict[str, Any]:
        n, d = X.shape
        w = jnp.ones(n, jnp.float32) if sample_weight is None else jnp.asarray(sample_weight)
        family = self.get("family", "gaussian")
        loss = {"gaussian": "squared", "binomial": "logistic",
                "poisson": "poisson", "gamma": "gamma"}.get(family)
        if loss is None:
            raise ValueError(f"unsupported GLM family {family!r}")
        if isinstance(X, SparseMatrix):
            res = sparse_fista_fit(
                X, jnp.asarray(y), w, float(self.get("reg_param", 0.0)), 0.0,
                loss=loss, fit_intercept=self.get("fit_intercept", True),
                max_iter=int(self.get("max_iter", 50)),
                tol=float(self.get("tol", 1e-6)))
            return {"coef": np.asarray(res.coef),
                    "intercept": np.asarray(res.intercept),
                    "kind": "glm", "family": family,
                    "n_iter": int(res.n_iter)}
        Xj, yj = jnp.asarray(X), jnp.asarray(y)
        Xs, mean, scale = standardize(Xj, w, center=self.get("fit_intercept", True))
        res = fista_fit(Xs, yj, w, jnp.float32(self.get("reg_param", 0.0)),
                        jnp.float32(0.0), loss=loss,
                        fit_intercept=self.get("fit_intercept", True),
                        max_iter=int(self.get("max_iter", 50)),
                        tol=float(self.get("tol", 1e-6)))
        res = unscale_params(res, mean, scale, 1)
        return {"coef": np.asarray(res.coef), "intercept": np.asarray(res.intercept),
                "kind": "glm", "family": family, "n_iter": int(res.n_iter)}

    def fit_arrays_grid(self, X, y, fold_weights, grids):
        family = self.get("family", "gaussian")
        loss = {"gaussian": "squared", "binomial": "logistic",
                "poisson": "poisson", "gamma": "gamma"}[family]
        return _grouped_grid_fit(
            self, X, y, fold_weights, grids, loss=loss, n_classes=2,
            l2l1=lambda m: (float(m.get("reg_param", 0.0)), 0.0),
            fitted_extra={"kind": "glm", "family": family})


class GLMPredictionModel(LinearPredictionModel):
    """≙ GeneralizedLinearRegressionModel.predict: apply the family's inverse
    link g⁻¹(η) to the linear predictor (exp for poisson/gamma log link,
    sigmoid for binomial logit; identity for gaussian)."""

    _INVERSE_LINK = {
        "poisson": lambda eta: np.exp(np.clip(eta, -30.0, 30.0)),
        "gamma": lambda eta: np.exp(np.clip(eta, -30.0, 30.0)),
        "binomial": lambda eta: 1.0 / (1.0 + np.exp(-np.clip(eta, -30.0, 30.0))),
        "gaussian": lambda eta: eta,
    }

    def predict_arrays(self, X) -> Dict[str, np.ndarray]:
        coef = np.asarray(self.fitted["coef"], dtype=np.float32)
        intercept = np.asarray(self.fitted["intercept"], dtype=np.float32)
        lin = np.asarray(X @ coef) if isinstance(X, SparseMatrix) else X @ coef
        eta = lin + (intercept[0] if intercept.ndim else intercept)
        inv = self._INVERSE_LINK[self.fitted.get("family", "gaussian")]
        return {"prediction": inv(eta).astype(np.float32)}


OpGeneralizedLinearRegression.model_cls = GLMPredictionModel


class NaiveBayesModel(PredictionModel):
    """Fitted multinomial NB: log_prior [C], log_prob [C,D]."""

    def device_scores(self, Xd, full: bool = False) -> Dict[str, Any]:
        logits = (jnp.maximum(Xd, 0.0) @ jnp.asarray(self.fitted["log_prob"]).T
                  + jnp.asarray(self.fitted["log_prior"]))
        prob = jax.nn.softmax(logits, axis=-1)
        out = {"prediction": jnp.argmax(logits, axis=1).astype(jnp.float32),
               "probability": prob}
        if prob.shape[1] == 2:
            out["scores"] = prob[:, 1]
        if full:
            out["rawPrediction"] = logits
        return out

    def predict_arrays(self, X: np.ndarray) -> Dict[str, np.ndarray]:
        log_prior = np.asarray(self.fitted["log_prior"])
        log_prob = np.asarray(self.fitted["log_prob"])
        logits = np.maximum(X, 0.0) @ log_prob.T + log_prior
        prob = _np_softmax(logits)
        return {"prediction": np.argmax(logits, axis=1).astype(np.float32),
                "probability": prob, "rawPrediction": logits}


class OpNaiveBayes(PredictorEstimator):
    """≙ OpNaiveBayes (multinomial, smoothing=1.0 default)."""

    model_cls = NaiveBayesModel

    def __init__(self, smoothing: float = 1.0, **kw):
        super().__init__(smoothing=smoothing, **kw)

    def fit_arrays(self, X, y, sample_weight=None) -> Dict[str, Any]:
        n = X.shape[0]
        w = jnp.ones(n, jnp.float32) if sample_weight is None else jnp.asarray(sample_weight)
        C = _n_classes(y)
        log_prior, log_prob = naive_bayes_fit(
            jnp.asarray(X), jnp.asarray(y), w,
            jnp.float32(self.get("smoothing", 1.0)), n_classes=C)
        return {"log_prior": np.asarray(log_prior), "log_prob": np.asarray(log_prob),
                "kind": "naive_bayes", "n_classes": C}


class MLPClassificationModel(PredictionModel):
    """Fitted MLP: list of (W, b) per layer."""

    def device_scores(self, Xd, full: bool = False) -> Dict[str, Any]:
        h = Xd
        n_layers = self.fitted["n_layers"]
        for i in range(n_layers):
            h = h @ jnp.asarray(self.fitted[f"W{i}"]) + jnp.asarray(self.fitted[f"b{i}"])
            if i < n_layers - 1:
                h = jax.nn.relu(h)
        prob = jax.nn.softmax(h, axis=-1)
        out = {"prediction": jnp.argmax(h, axis=1).astype(jnp.float32),
               "probability": prob}
        if prob.shape[1] == 2:
            out["scores"] = prob[:, 1]
        if full:
            out["rawPrediction"] = h
        return out

    def predict_arrays(self, X: np.ndarray) -> Dict[str, np.ndarray]:
        h = np.asarray(X, dtype=np.float32)
        n_layers = self.fitted["n_layers"]
        for i in range(n_layers):
            W = np.asarray(self.fitted[f"W{i}"])
            b = np.asarray(self.fitted[f"b{i}"])
            h = h @ W + b
            if i < n_layers - 1:
                h = np.maximum(h, 0.0)
        logits = h
        prob = _np_softmax(logits)
        return {"prediction": np.argmax(logits, axis=1).astype(np.float32),
                "probability": prob, "rawPrediction": logits}


class OpMultilayerPerceptronClassifier(PredictorEstimator):
    """≙ OpMultilayerPerceptronClassifier: small feed-forward net, full-batch
    Adam (the reference uses L-BFGS on a sigmoid net; relu+adam is the
    TPU-idiomatic equivalent)."""

    model_cls = MLPClassificationModel

    def __init__(self, hidden_layers=(10,), max_iter: int = 200,
                 step_size: float = 0.05, seed: int = 42, **kw):
        super().__init__(hidden_layers=tuple(hidden_layers), max_iter=max_iter,
                         step_size=step_size, seed=seed, **kw)

    def fit_arrays(self, X, y, sample_weight=None) -> Dict[str, Any]:
        import optax
        n, d = X.shape
        C = _n_classes(y)
        sizes = [d] + list(self.get("hidden_layers", (10,))) + [C]
        key = jax.random.PRNGKey(int(self.get("seed", 42)))
        params = []
        for i in range(len(sizes) - 1):
            key, k1 = jax.random.split(key)
            W = jax.random.normal(k1, (sizes[i], sizes[i + 1]),
                                  jnp.float32) * jnp.sqrt(2.0 / sizes[i])
            params.append((W, jnp.zeros(sizes[i + 1], jnp.float32)))
        Xj = jnp.asarray(X)
        yj = jnp.asarray(y, dtype=jnp.int32)
        w = jnp.ones(n, jnp.float32) if sample_weight is None else jnp.asarray(sample_weight)

        def forward(params, x):
            h = x
            for i, (W, b) in enumerate(params):
                h = h @ W + b
                if i < len(params) - 1:
                    h = jax.nn.relu(h)
            return h

        def loss_fn(params):
            logits = forward(params, Xj)
            ls = optax.softmax_cross_entropy_with_integer_labels(logits, yj)
            return jnp.sum(w * ls) / jnp.sum(w)

        opt = optax.adam(float(self.get("step_size", 0.05)))
        state = opt.init(params)

        @jax.jit
        def step(params, state):
            loss, grads = jax.value_and_grad(loss_fn)(params)
            updates, state = opt.update(grads, state)
            return optax.apply_updates(params, updates), state, loss

        for _ in range(int(self.get("max_iter", 200))):
            params, state, loss = step(params, state)
        fitted: Dict[str, Any] = {"kind": "mlp", "n_layers": len(params),
                                  "n_classes": C}
        for i, (W, b) in enumerate(params):
            fitted[f"W{i}"] = np.asarray(W)
            fitted[f"b{i}"] = np.asarray(b)
        return fitted
