"""Model stages (≙ core/.../stages/impl/{classification,regression} and the
sparkwrappers.specific OpPredictorWrapper machinery)."""

from .base import (PredictionModel, PredictorEstimator, extract_xy,
                   prediction_column)
from .external import ExternalEstimator, ExternalModel, wrap_estimator
from .linear import (LinearPredictionModel, MLPClassificationModel,
                     NaiveBayesModel, OpGeneralizedLinearRegression,
                     OpLinearRegression, OpLinearSVC, OpLogisticRegression,
                     OpMultilayerPerceptronClassifier, OpNaiveBayes)
from .trees import (OpDecisionTreeClassifier, OpDecisionTreeRegressor,
                    OpGBTClassifier, OpGBTRegressor, OpRandomForestClassifier,
                    OpRandomForestRegressor, OpXGBoostClassifier,
                    OpXGBoostRegressor, TreeEnsembleModel)

MODEL_REGISTRY = {
    cls.__name__: cls for cls in [
        LinearPredictionModel, NaiveBayesModel, MLPClassificationModel,
        TreeEnsembleModel, ExternalEstimator, ExternalModel,
        OpLogisticRegression, OpLinearSVC, OpLinearRegression, OpNaiveBayes,
        OpGeneralizedLinearRegression, OpMultilayerPerceptronClassifier,
        OpRandomForestClassifier, OpRandomForestRegressor,
        OpDecisionTreeClassifier, OpDecisionTreeRegressor,
        OpGBTClassifier, OpGBTRegressor, OpXGBoostClassifier,
        OpXGBoostRegressor,
    ]
}

__all__ = list(MODEL_REGISTRY) + [
    "PredictionModel", "PredictorEstimator", "extract_xy", "prediction_column",
    "MODEL_REGISTRY", "wrap_estimator",
]
