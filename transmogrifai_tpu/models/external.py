"""External-model bridge — wrap ANY array-in/array-out estimator as a model
stage usable in ``ModelCandidate`` (reference: the sparkwrappers layer —
core/.../stages/sparkwrappers/generic/SwUnaryEstimator.scala wraps arbitrary
Spark estimators, specific/OpPredictorWrapper.scala:67 adapts predictors to
the (RealNN, OPVector) → Prediction contract; this is how XGBoost entered the
reference's selector).

The TPU-native contract is functional, not class-reflective: the external
model is a pair of pure functions over numpy arrays

    fit(X, y, sample_weight=None, **hyperparams) -> params: dict[str, array]
    predict(params: dict, X) -> prediction array | dict

``params`` must contain only arrays / JSON-safe scalars — it checkpoints into
the standard ``params.npz`` + manifest layout with NO pickling.  Reload
resolves the functions by import path (``module:qualname``, ≙
ReflectionUtils.classForName), which ``wrap_estimator`` derives automatically
for module-level callables.

``predict`` may return:
  * a 1-D array — used as ``prediction`` directly (regressors),
  * a 2-D array — class probabilities; ``prediction`` = argmax,
  * a dict with ``prediction`` / ``probability`` / ``rawPrediction`` keys.
"""

from __future__ import annotations

import importlib
from typing import Any, Callable, Dict, Optional

import numpy as np

from .base import PredictionModel, PredictorEstimator

# ctor/config keys that are NOT hyperparameters of the wrapped model
_RESERVED = ("fit_spec", "predict_spec", "uid")


def resolve_callable(spec: str) -> Callable:
    """``"module:qualname"`` → the callable it names."""
    mod_name, _, qual = spec.partition(":")
    if not mod_name or not qual:
        raise ValueError(
            f"external-model spec {spec!r} must look like 'module:qualname'")
    obj: Any = importlib.import_module(mod_name)
    for part in qual.split("."):
        obj = getattr(obj, part)
    if not callable(obj):
        raise TypeError(f"external-model spec {spec!r} is not callable")
    return obj


def spec_of(fn: Callable) -> Optional[str]:
    """Derive the import spec of a module-level callable; None when the
    callable is a lambda / closure / local and cannot be re-imported."""
    mod = getattr(fn, "__module__", None)
    qual = getattr(fn, "__qualname__", "")
    if not mod or not qual or "<" in qual:
        return None
    try:
        if resolve_callable(f"{mod}:{qual}") is fn:
            return f"{mod}:{qual}"
    except Exception:  # noqa: BLE001 — nested/renamed attribute
        pass
    return None


def _normalize_prediction(out: Any) -> Dict[str, np.ndarray]:
    if isinstance(out, dict):
        res = {k: np.asarray(v) for k, v in out.items() if v is not None}
        if "prediction" not in res:
            prob = res.get("probability")
            if prob is None:
                raise ValueError(
                    "external predict() dict needs 'prediction' or "
                    "'probability'")
            res["prediction"] = np.argmax(prob, axis=1).astype(np.float32)
        return res
    arr = np.asarray(out)
    if arr.ndim == 2:
        return {"prediction": np.argmax(arr, axis=1).astype(np.float32),
                "probability": arr, "rawPrediction": arr}
    return {"prediction": arr.astype(np.float32)}


class ExternalModel(PredictionModel):
    """Fitted wrapped model.  ``fitted`` holds exactly what the user's
    ``fit`` returned; ``predict_spec`` (ctor param) re-binds ``predict`` on
    reload — no pickle anywhere."""

    def __init__(self, **params):
        super().__init__(**params)
        # bound post-construction by ExternalEstimator's model factory;
        # reload paths resolve lazily via predict_spec instead
        self._predict_fn: Optional[Callable] = None

    def _predict(self) -> Callable:
        if self._predict_fn is None:
            spec = self.get("predict_spec")
            if not spec:
                raise RuntimeError(
                    "ExternalModel has no predict function: construct via "
                    "wrap_estimator with an importable (module-level) predict "
                    "callable, or set predict_spec='module:qualname'")
            self._predict_fn = resolve_callable(spec)
        return self._predict_fn

    def predict_arrays(self, X: np.ndarray) -> Dict[str, np.ndarray]:
        out = self._predict()(dict(self.fitted), np.asarray(X, np.float32))
        return _normalize_prediction(out)

    def check_serializable(self) -> None:
        if not self.get("predict_spec"):
            raise ValueError(
                "cannot save an ExternalModel whose predict function is not "
                "importable: define predict at module level (so "
                "'module:qualname' resolves to it) or set predict_spec "
                "explicitly before saving")

    def save_extra(self):
        self.check_serializable()
        return super().save_extra()


class ExternalEstimator(PredictorEstimator):
    """(label, features) → Prediction stage around user fit/predict functions
    (≙ SwUnaryEstimator + OpPredictorWrapper).  Grid-searchable: every
    non-reserved param — including grid points set by the ModelSelector — is
    forwarded to ``fit`` as a keyword hyperparameter."""

    model_cls = ExternalModel

    def __init__(self, fit_fn: Optional[Callable] = None,
                 predict_fn: Optional[Callable] = None, **params):
        super().__init__(**params)
        self._fit_fn = fit_fn
        self._predict_fn = predict_fn
        # derive import specs so the fitted stage serializes pickle-free
        if fit_fn is not None and not self.get("fit_spec"):
            s = spec_of(fit_fn)
            if s:
                self.set("fit_spec", s)
        if predict_fn is not None and not self.get("predict_spec"):
            s = spec_of(predict_fn)
            if s:
                self.set("predict_spec", s)

        # models built anywhere (CV metric path constructs them via
        # est.model_cls) get the LIVE predict callable, so non-importable
        # callables still train/score in-memory; only save() requires a spec
        def _model_factory(**kw) -> ExternalModel:
            m = ExternalModel(**kw)
            if m._predict_fn is None:
                m._predict_fn = self._predict_fn
            return m

        self.model_cls = _model_factory  # shadows the class attr

    def _fit(self) -> Callable:
        if self._fit_fn is None:
            spec = self.get("fit_spec")
            if not spec:
                raise RuntimeError(
                    "ExternalEstimator has no fit function: pass fit_fn= or "
                    "fit_spec='module:qualname'")
            self._fit_fn = resolve_callable(spec)
        return self._fit_fn

    def _hyperparams(self) -> Dict[str, Any]:
        return {k: v for k, v in self._params.items() if k not in _RESERVED}

    def fit_arrays(self, X, y, sample_weight=None) -> Dict[str, Any]:
        X = np.asarray(X, np.float32)
        y = np.asarray(y, np.float32)
        if sample_weight is not None:
            sample_weight = np.asarray(sample_weight, np.float32)
        fitted = self._fit()(X, y, sample_weight=sample_weight,
                             **self._hyperparams())
        if not isinstance(fitted, dict):
            raise TypeError(
                f"external fit() must return a dict of arrays, got "
                f"{type(fitted).__name__}")
        return fitted


def wrap_estimator(fit: Callable, predict: Callable,
                   **hyperparams) -> ExternalEstimator:
    """Turn a (fit, predict) pair into a selector-ready estimator stage.

    >>> cand = ModelCandidate(wrap_estimator(my_fit, my_predict),
    ...                       grid(alpha=[0.1, 1.0]), "MyModel")

    For ``model.save()`` to round-trip, ``fit`` and ``predict`` must be
    module-level callables (re-importable by path); otherwise training and
    scoring work in-memory but ``save`` of the winning model will fail with
    an actionable error.
    """
    return ExternalEstimator(fit_fn=fit, predict_fn=predict, **hyperparams)
