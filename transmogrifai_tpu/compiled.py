"""Compiled scoring — the fitted transformer DAG as ONE XLA program.

The reference's score path bulk-applies row closures per layer and persists
every K stages to break Catalyst (FitStagesUtil.scala:96,134-165).  Here
every maximal device-resident stretch of the DAG — vectorizer models,
VectorsCombiner, SanityChecker slice, the selected model's forward — traces
into its own jitted program: one compile per segment (cached across calls),
one host→device transfer of each segment's frontier columns, one
device→host transfer of the requested results per ``score()`` call
(SURVEY.md §2.6 P5: HBM residency replaces ``.persist()``).  For a typical
numeric workflow that is ONE fused program; text-heavy DAGs get a device
segment before and after their string stages.

String/object-valued stages (tokenizers, validators, pick-list maps) cannot
live in an XLA program; they run eagerly between the compiled segments.  A
stage whose ``is_device_op`` flag is optimistic but whose transform turns
out not to be traceable is demoted automatically (one retry, then it joins
the host segments for the lifetime of the program).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

import jax
import numpy as np

from .columns import Column, ColumnBatch
from .stages.base import Transformer


class _StageTraceError(Exception):
    """Tracing failed inside a specific stage; carries the stage uid."""

    def __init__(self, uid: str, cause: Exception):
        super().__init__(uid)
        self.uid = uid
        self.cause = cause


class ScoreProgram:
    """A fitted DAG compiled for repeated scoring.

    ``program = ScoreProgram(stages, result_names)`` then
    ``scored = program(batch)`` — equivalent to ``apply_dag`` but every
    maximal contiguous run of device-traceable stages executes as one jitted
    XLA program (host stages eager in between).  jax's jit cache keys on the
    frontier shapes, so calls with a fixed schema compile each segment
    exactly once.
    """

    def __init__(self, dag: Sequence, result_names: Sequence[str]):
        # accept a layered DAG or a flat stage list; within a layer, order
        # host ops before device ops (any within-layer order is topologically
        # legal) so device segments coalesce instead of fragmenting
        layers = ([list(l) for l in dag]
                  if dag and isinstance(dag[0], (list, tuple)) else [list(dag)])
        self.stages: List[Transformer] = []
        for layer in layers:
            self.stages.extend(sorted(layer, key=lambda s: s.is_device_op))
        self.result_names = list(result_names)
        self._demoted: Set[str] = set()   # uids proven untraceable
        self._jitted: Dict[Tuple[str, ...], Any] = {}
        self._metas: Dict[Tuple[str, ...], Dict[str, Any]] = {}

    # -- partition ----------------------------------------------------------
    def _partition(self, batch: ColumnBatch) -> List[Tuple[bool, List[Transformer]]]:
        """Split stages (already in topo order) into alternating
        (is_device_segment, stages) groups: every maximal contiguous stretch
        of device ops over array-resident inputs becomes its own jitted
        segment, with host stages eager in between (a text-heavy DAG can have
        device vectorizers BEFORE its string stages and the fused model tail
        after — both compile)."""
        arrayish: Dict[str, bool] = {
            name: batch[name].is_device for name in batch.names()}
        segments: List[Tuple[bool, List[Transformer]]] = []
        for st in self.stages:
            ok = (st.is_device_op and st.uid not in self._demoted
                  and all(arrayish.get(f.name, False)
                          for f in st.input_features))
            for f in st.output_features:
                # host stages may still emit array columns (e.g. one-hot on
                # strings); simulate with the same rule Column.is_device uses
                arrayish[f.name] = True if ok else _kind_arrayish(f.kind)
            if segments and segments[-1][0] == ok:
                segments[-1][1].append(st)
            else:
                segments.append((ok, [st]))
        return segments

    # -- execution ----------------------------------------------------------
    def __call__(self, batch: ColumnBatch, keep_intermediate: bool = False
                 ) -> ColumnBatch:
        for _attempt in range(len(self.stages) + 1):
            segments = self._partition(batch)
            b = batch
            try:
                for i, (is_dev, stages) in enumerate(segments):
                    if not is_dev:
                        for st in stages:
                            b = st.transform_batch(b)
                        continue
                    later = [st for _, seg in segments[i + 1:] for st in seg]
                    b = self._apply_run(b, stages, later, keep_intermediate)
            except _StageTraceError as e:
                # demote the offending stage to the host segments and
                # re-partition; transforms are pure so re-running the
                # prologue on the original batch is safe
                self._demoted.add(e.uid)
                continue
            return b
        raise RuntimeError("ScoreProgram failed to converge on a partition")

    def _wanted_outputs(self, run: List[Transformer], later: List[Transformer],
                        keep_intermediate: bool) -> List[str]:
        produced = [f.name for st in run for f in st.output_features]
        if keep_intermediate:
            return produced
        needed = set(self.result_names)
        for st in later:
            needed.update(f.name for f in st.input_features)
        return [n for n in produced if n in needed]

    def _apply_run(self, batch: ColumnBatch, run: List[Transformer],
                   later: List[Transformer], keep_intermediate: bool
                   ) -> ColumnBatch:
        key = tuple(st.uid for st in run) + (keep_intermediate,)
        frontier = sorted({f.name for st in run for f in st.input_features
                           if f.name in batch})
        # _partition simulates host-stage outputs by kind; validate against
        # the actual columns and demote consumers of any misprediction (e.g.
        # a numeric-kinded host stage that emitted an object array)
        host_cols = [n for n in frontier if not batch[n].is_device]
        if host_cols:
            offender = next(st for st in run if any(
                f.name in host_cols for f in st.input_features))
            raise _StageTraceError(offender.uid, TypeError(
                f"frontier columns {host_cols} are host-resident"))
        out_names = self._wanted_outputs(run, later, keep_intermediate)
        kinds = {n: batch[n].kind for n in frontier}
        metas_in = {n: batch[n].meta for n in frontier}

        if key not in self._jitted:
            metas_out: Dict[str, Any] = {}

            def traced(arrays: Dict[str, Tuple[Any, Any]]):
                # row count from the traced arrays (NOT the captured batch:
                # jit retraces on new shapes and closures would be stale)
                v0 = next(iter(arrays.values()))[0]
                n_rows = (next(iter(v0.values())).shape[0]
                          if isinstance(v0, dict) else v0.shape[0])
                cols = {n: Column(kinds[n], v, m, meta=metas_in[n])
                        for n, (v, m) in arrays.items()}
                b = ColumnBatch(dict(cols), n_rows)
                for st in run:
                    try:
                        b = st.transform_batch(b)
                    except Exception as e:  # noqa: BLE001 — demotion signal
                        raise _StageTraceError(st.uid, e) from e
                out = {}
                for n in out_names:
                    c = b[n]
                    metas_out[n] = (c.meta, c.kind)
                    out[n] = (c.values, c.mask)
                return out

            self._jitted[key] = jax.jit(traced)
            self._metas[key] = metas_out

        def _prep(v):
            # float32 columns ride the bf16 wire format to the device (see
            # columns.to_device_f32); other dtypes transfer as-is inside jit
            if isinstance(v, np.ndarray) and v.dtype == np.float32:
                from .columns import to_device_f32
                return to_device_f32(v)
            return v

        arrays = {n: (_prep(batch[n].values), batch[n].mask)
                  for n in frontier}
        try:
            out = self._jitted[key](arrays)
        except _StageTraceError:
            self._jitted.pop(key, None)
            self._metas.pop(key, None)
            raise
        except Exception:
            # unexpected jit-boundary failure: never break scoring — run the
            # segment eagerly (≙ apply_dag) and stop attempting to compile
            self._jitted.pop(key, None)
            self._metas.pop(key, None)
            self._demoted.update(st.uid for st in run)
            b = batch
            for st in run:
                b = st.transform_batch(b)
            return b
        metas_out = self._metas[key]
        new_cols = {}
        for n, (v, m) in out.items():
            meta, kind = metas_out[n]
            new_cols[n] = Column(kind, v, m, meta=meta)
        return batch.with_columns(new_cols)


def _kind_arrayish(kind) -> bool:
    """Static analog of Column.is_device for a feature kind: does a column of
    this kind hold dense arrays (vs host object arrays)?"""
    from .types import Geolocation, OPVector, Prediction, is_numeric_kind
    if kind is None:
        return False
    if issubclass(kind, (OPVector, Prediction, Geolocation)):
        return True
    if is_numeric_kind(kind):
        return True
    return False
