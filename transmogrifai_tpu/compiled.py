"""Compiled scoring — the fitted transformer DAG as ONE XLA program.

The reference's score path bulk-applies row closures per layer and persists
every K stages to break Catalyst (FitStagesUtil.scala:96,134-165).  Here
every maximal device-resident stretch of the DAG — vectorizer models,
VectorsCombiner, SanityChecker slice, the selected model's forward — traces
into its own jitted program: one compile per segment (cached across calls),
one host→device transfer of each segment's frontier columns, one
device→host transfer of the requested results per ``score()`` call
(SURVEY.md §2.6 P5: HBM residency replaces ``.persist()``).  For a typical
numeric workflow that is ONE fused program; text-heavy DAGs get a device
segment before and after their string stages.

Stages over strings/objects join device segments through the STAGED
protocol (``Transformer.transform_staged``): their host prologue runs
before the segment and contributes compact wire arrays (token ids, vocab
codes) to the frontier, and their traceable body runs inside the fused
program — so even a text-heavy vectorizer layer compiles into one XLA
program.  Stages with neither a device nor a staged form run eagerly
between the compiled segments.  A stage whose ``is_device_op``/staging flag
is optimistic but whose transform turns out not to be traceable is demoted
automatically (one retry, then it joins the host segments for the lifetime
of the program).
"""

from __future__ import annotations

import contextlib
import threading
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

import jax
import numpy as np

from .columns import Column, ColumnBatch
from .resilience import maybe_inject, record_failure
from .stages.base import Transformer

_WIRE_SEP = "\x00"      # wire-entry names: "<uid>\x00<key>" — never a column

# process-wide count of fused-program TRACES (each one implies an XLA
# compile).  The serving layer's "no online recompile after warmup" guarantee
# is asserted against this: snapshot after warmup, require no growth under
# traffic.  Incremented inside traced() — that body only executes while jax
# is actually tracing, never on a jit cache hit.
_TRACE_COUNT = [0]

# threads whose traces are deliberately off the books: AOT export warms the
# ladder at save() time, and a save running concurrently with a serving
# engine (lifecycle retrain+promote, the hot-reload tests) must not land its
# warmup traces inside the engine's online-trace measurement window — the
# engine would blame itself and demote to the local fallback.  jax traces on
# the calling thread, so a thread-local flag attributes exactly the
# suppressing thread's traces and nothing else.
_TRACE_LOCAL = threading.local()


def trace_count() -> int:
    return _TRACE_COUNT[0]


@contextlib.contextmanager
def suppress_trace_count():
    """Traces on THIS thread don't count toward ``trace_count()`` while the
    context is open (save-time AOT export warmup — see aot.py)."""
    prev = getattr(_TRACE_LOCAL, "suppress", False)
    _TRACE_LOCAL.suppress = True
    try:
        yield
    finally:
        _TRACE_LOCAL.suppress = prev


def compile_attribution() -> Dict[str, Any]:
    """Traces vs actual backend compiles vs persistent-cache hits, in one
    snapshot.  A trace that ends in a cache hit costs milliseconds; one that
    reaches the backend compiler costs seconds — warmup asserts should
    compare against ``new_compiles`` (cache-aware), not ``traces``."""
    from .profiling import compile_seconds, compile_stats, new_compile_count
    return {"traces": trace_count(),
            "new_compiles": new_compile_count(),
            "compile_seconds": round(compile_seconds(), 4),
            **compile_stats()}


def _args_sig(arrays) -> Optional[str]:
    """Canonical JSON input-aval signature of one call's argument pytree —
    the VARIANT coordinate for per-(key, sig) AOT executables.  The program
    key carries only (stages, keep_intermediate, rows); sparse frontier
    columns add an nnz-capacity degree of freedom only the avals see."""
    try:
        import json

        from .aot_registry import args_signature
        return json.dumps(args_signature(arrays), sort_keys=True,
                          default=repr)
    except Exception:  # noqa: BLE001 — unsignable args are just unexported
        return None


class _StageTraceError(Exception):
    """Tracing failed inside a specific stage; carries the stage uid."""

    def __init__(self, uid: str, cause: Exception):
        super().__init__(uid)
        self.uid = uid
        self.cause = cause


class ScoreProgram:
    """A fitted DAG compiled for repeated scoring.

    ``program = ScoreProgram(stages, result_names)`` then
    ``scored = program(batch)`` — equivalent to ``apply_dag`` but every
    maximal contiguous run of device-traceable (or staged) stages executes
    as one jitted XLA program (host stages eager in between).  jax's jit
    cache keys on the frontier shapes, so calls with a fixed schema compile
    each segment exactly once.
    """

    def __init__(self, dag: Sequence, result_names: Sequence[str]):
        # accept a layered DAG or a flat stage list; within a layer, order
        # host ops before device/staged ops (any within-layer order is
        # topologically legal) so device segments coalesce instead of
        # fragmenting
        layers = ([list(l) for l in dag]
                  if dag and isinstance(dag[0], (list, tuple)) else [list(dag)])
        self.stages: List[Transformer] = []
        for layer in layers:
            self.stages.extend(sorted(
                layer, key=lambda s: bool(s.is_device_op
                                          or s.supports_staging)))
        self.result_names = list(result_names)
        self._demoted: Set[str] = set()   # uids proven untraceable
        self._jitted: Dict[Tuple, Any] = {}
        self._metas: Dict[Tuple, Dict[str, Any]] = {}
        # AOT seams (see aot.py): per-key input avals captured at first call
        # (what export lowers against), and keys whose entry is a
        # deserialized pre-compiled executable rather than a jit wrapper
        self._input_specs: Dict[Tuple, Any] = {}
        self._aot_installed: Set[Tuple] = set()
        # aval-variant seam (ISSUE 19): the program-table key carries only
        # (stage uids, keep_intermediate, rows) — sparse frontier columns
        # add an nnz-capacity degree of freedom the key cannot see.  Every
        # distinct input-aval signature observed per key records its specs
        # here (what export lowers against), and pre-compiled executables
        # for specific signatures install per (key, sig) so one padded row
        # rung serves the whole nnz ladder with zero traces.
        self._input_spec_variants: Dict[Tuple, Dict[str, Any]] = {}
        self._aot_variants: Dict[Tuple[Tuple, str], Tuple] = {}
        # (key, sig) pairs already offered to the fleet registry — a miss is
        # memoized so steady-state calls pay zero registry lookups
        self._registry_checked: Set[Tuple] = set()
        # model-content digest tying this program to the fleet registry
        # (aot_registry.py); set by workflow load/save, None = no registry
        self.registry_family: Optional[str] = None

    def install_executable(self, key: Tuple, fn: Any,
                           canon_out: Dict[str, str],
                           metas: Dict[str, Any],
                           sig: Optional[str] = None) -> None:
        """Install a deserialized AOT executable for ``key`` — subsequent
        calls at that exact (stages, rows) signature dispatch straight to it
        with zero traces and zero compiles.  A call-time failure (shape or
        ABI drift the stamp missed) uninstalls it and falls back to jit.

        With ``sig`` (an input-aval signature, see ``_args_sig``) the
        executable installs as a VARIANT for that exact signature only: the
        key's jit entry stays intact, so calls at other signatures (e.g.
        other sparse nnz capacities) still trace/compile correctly instead
        of crashing into a mis-shaped executable."""
        if sig is not None:
            self._aot_variants[(key, sig)] = (fn, dict(canon_out),
                                              dict(metas))
            return
        self._jitted[key] = (fn, dict(canon_out))
        self._metas[key] = dict(metas)
        self._aot_installed.add(key)

    def aot_installed_count(self) -> int:
        return len(self._aot_installed) + len(self._aot_variants)

    # -- partition ----------------------------------------------------------
    def _partition(self, batch: ColumnBatch) -> List[Tuple[bool, List[Transformer]]]:
        """Split stages (already in topo order) into alternating
        (is_device_segment, stages) groups: every maximal contiguous stretch
        of device ops over array-resident inputs — plus staged stages whose
        inputs are materialized before the segment — becomes its own jitted
        segment, with host stages eager in between."""
        arrayish: Dict[str, bool] = {
            name: batch[name].is_device for name in batch.names()}
        segments: List[Tuple[bool, List[Transformer]]] = []
        seg_outputs: Set[str] = set()   # outputs of the OPEN device segment
        for st in self.stages:
            dev_ok = (st.is_device_op and st.uid not in self._demoted
                      and all(arrayish.get(f.name, False)
                              for f in st.input_features))
            # a staged stage's host prologue runs BEFORE the segment, so its
            # inputs must not be produced inside the same segment
            staged_ok = (not dev_ok and st.supports_staging
                         and st.uid not in self._demoted
                         and not any(f.name in seg_outputs
                                     for f in st.input_features))
            ok = dev_ok or staged_ok
            for f in st.output_features:
                # host stages may still emit array columns (e.g. one-hot on
                # strings); simulate with the same rule Column.is_device uses
                arrayish[f.name] = True if ok else _kind_arrayish(f.kind)
            if segments and segments[-1][0] == ok:
                segments[-1][1].append(st)
            else:
                segments.append((ok, [st]))
                seg_outputs = set()
            if ok:
                seg_outputs.update(f.name for f in st.output_features)
        return segments

    # -- execution ----------------------------------------------------------
    def __call__(self, batch: ColumnBatch, keep_intermediate: bool = False
                 ) -> ColumnBatch:
        for _attempt in range(len(self.stages) + 1):
            segments = self._partition(batch)
            b = batch
            try:
                for i, (is_dev, stages) in enumerate(segments):
                    if not is_dev:
                        for st in stages:
                            b = st.transform_batch(b)
                        continue
                    later = [st for _, seg in segments[i + 1:] for st in seg]
                    b = self._apply_run(b, stages, later, keep_intermediate)
            except _StageTraceError as e:
                # demote the offending stage to the host segments and
                # re-partition; transforms are pure so re-running the
                # prologue on the original batch is safe
                record_failure(e.uid, "demoted", e.cause,
                               point="compiled.trace",
                               fallback="host segment")
                self._demoted.add(e.uid)
                continue
            return b
        raise RuntimeError("ScoreProgram failed to converge on a partition")

    def _wanted_outputs(self, run: List[Transformer], later: List[Transformer],
                        keep_intermediate: bool) -> List[str]:
        produced = [f.name for st in run for f in st.output_features]
        if keep_intermediate:
            return produced
        needed = set(self.result_names)
        for st in later:
            needed.update(f.name for f in st.input_features)
        return [n for n in produced if n in needed]

    def _apply_run(self, batch: ColumnBatch, run: List[Transformer],
                   later: List[Transformer], keep_intermediate: bool
                   ) -> ColumnBatch:
        # staged = stages whose inputs are NOT all array-resident right now;
        # their host prologue supplies wire arrays instead of columns
        staged_fns: Dict[str, Any] = {}
        wires: Dict[str, Any] = {}
        for st in run:
            if all(batch[f.name].is_device for f in st.input_features
                   if f.name in batch):
                continue
            res = None
            try:
                res = st.transform_staged(batch)
            except Exception as e:  # noqa: BLE001 — demotion signal
                raise _StageTraceError(st.uid, e) from e
            if res is None:
                raise _StageTraceError(st.uid, TypeError(
                    "stage has host inputs and no staged form"))
            wire, fn = res
            staged_fns[st.uid] = fn
            for k, v in wire.items():
                wires[st.uid + _WIRE_SEP + k] = v

        key = (tuple(st.uid for st in run), keep_intermediate, len(batch))
        frontier = sorted({f.name for st in run
                           if st.uid not in staged_fns
                           for f in st.input_features if f.name in batch})
        # canonical positional names at the jit boundary: stage uids are
        # process-global counters, so real column/wire names differ between
        # otherwise identical workflows — with them as pytree keys every new
        # process MISSES the persistent compilation cache and pays a full
        # XLA recompile of the fused program
        canon_in = {n: f"a{i}" for i, n in enumerate(
            frontier + sorted(wires))}
        # _partition simulates host-stage outputs by kind; validate against
        # the actual columns and demote consumers of any misprediction (e.g.
        # a numeric-kinded host stage that emitted an object array)
        host_cols = [n for n in frontier if not batch[n].is_device]
        if host_cols:
            offender = next(st for st in run if st.uid not in staged_fns
                            and any(f.name in host_cols
                                    for f in st.input_features))
            raise _StageTraceError(offender.uid, TypeError(
                f"frontier columns {host_cols} are host-resident"))
        out_names = self._wanted_outputs(run, later, keep_intermediate)
        kinds = {n: batch[n].kind for n in frontier}
        metas_in = {n: batch[n].meta for n in frontier}
        n_rows_static = len(batch)

        fresh = key not in self._jitted
        if fresh:
            metas_out: Dict[str, Any] = {}
            fns_at_trace = dict(staged_fns)
            inv_in = {c: n for n, c in canon_in.items()}
            canon_out = {n: f"o{i}" for i, n in enumerate(out_names)}

            def traced(arrays_c: Dict[str, Tuple[Any, Any]]):
                if not getattr(_TRACE_LOCAL, "suppress", False):
                    _TRACE_COUNT[0] += 1
                arrays = {inv_in[c]: vm for c, vm in arrays_c.items()}
                cols = {n: Column(kinds[n], v, m, meta=metas_in[n])
                        for n, (v, m) in arrays.items()
                        if _WIRE_SEP not in n}
                b = ColumnBatch(dict(cols), n_rows_static)
                for st in run:
                    try:
                        if st.uid in fns_at_trace:
                            sub = {k.split(_WIRE_SEP, 1)[1]: v
                                   for k, (v, _) in arrays.items()
                                   if k.startswith(st.uid + _WIRE_SEP)}
                            out_col = fns_at_trace[st.uid](sub)
                            (f,) = st.output_features
                            b = b.with_columns({f.name: out_col})
                        else:
                            b = st.transform_batch(b)
                    except _StageTraceError:
                        raise
                    except Exception as e:  # noqa: BLE001 — demotion signal
                        raise _StageTraceError(st.uid, e) from e
                out = {}
                for n in out_names:
                    c = b[n]
                    metas_out[n] = (c.meta, c.kind)
                    out[canon_out[n]] = (c.values, c.mask)
                return out

            self._jitted[key] = (jax.jit(traced), canon_out)
            self._metas[key] = metas_out

        def _prep(v):
            # float32 columns ride the bf16 wire format to the device (see
            # columns.to_device_f32); other dtypes transfer as-is inside jit
            if isinstance(v, np.ndarray) and v.dtype == np.float32:
                from .columns import to_device_f32
                return to_device_f32(v)
            return v

        arrays = {canon_in[n]: (_prep(batch[n].values), batch[n].mask)
                  for n in frontier}
        arrays.update({canon_in[k]: (_prep(v), None)
                       for k, v in wires.items()})
        sig = _args_sig(arrays)
        if key not in self._input_specs:
            try:
                # unsharded host-side avals — what AOT export lowers against
                self._input_specs[key] = jax.tree_util.tree_map(
                    lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), arrays)
            except Exception:  # noqa: BLE001 — a non-array wire entry just
                pass           # makes this key non-exportable
        if sig is not None and sig not in self._input_spec_variants.get(
                key, {}):
            try:
                # every observed aval signature keeps its own exportable
                # specs: sparse nnz capacities vary per call under one key
                self._input_spec_variants.setdefault(key, {})[sig] = \
                    jax.tree_util.tree_map(
                        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
                        arrays)
            except Exception:  # noqa: BLE001 — unexportable variant
                pass
        # host-resident wire args copy to the device inside the jit call (or
        # in the sharding block below); count them toward the phase's link
        # bytes BEFORE _shard turns them into jax Arrays
        from .profiling import add_host_link_bytes
        add_host_link_bytes(sum(
            a.nbytes for v, m in arrays.values() for a in (v, m)
            if isinstance(a, np.ndarray)))
        # multi-device: row-shard every per-row input over the mesh 'data'
        # axis — the fused program then runs as one GSPMD computation
        # (SURVEY §2.6 P1 on the scoring path; ≙ applyOpTransformations'
        # executor row map, FitStagesUtil.scala:96).  Non-row wires (packed
        # token words, per-row+1 lens) stay replicated.
        from .parallel.mesh import data_sharding, maybe_data_mesh
        mesh = maybe_data_mesh(n_rows_static)
        if mesh is not None:
            try:
                def _shard(x):
                    if (x is not None and getattr(x, "ndim", 0) >= 1
                            and x.shape[0] == n_rows_static):
                        return jax.device_put(x, data_sharding(mesh, x.ndim))
                    return x
                arrays = {k: (_shard(v), _shard(m))
                          for k, (v, m) in arrays.items()}
            except Exception as e:  # noqa: BLE001 — sharding is an
                # optimization; a failed reshard (e.g. RESOURCE_EXHAUSTED
                # near capacity) must fall back to the unsharded program,
                # never break scoring
                record_failure("compiled", "degraded", e,
                               point="compiled.shard",
                               fallback="unsharded program")
        if (mesh is None and key not in self._aot_installed
                and (key, sig) not in self._aot_variants
                and (key, sig) not in self._registry_checked):
            # fleet-registry seam: a published executable for this exact
            # (family, stages, rows, avals) installs over the untraced jit
            # entry (or as an aval variant when the signature is known) —
            # the dispatch below then runs with zero compiles.  Misses are
            # memoized per (key, sig) so steady-state traffic pays zero
            # registry lookups.
            self._registry_checked.add((key, sig))
            from .aot_registry import try_install_score
            try_install_score(self, key, arrays, sig=sig)
        if mesh is None and sig is not None:
            var = self._aot_variants.get((key, sig))
            if var is not None:
                # variant fast path: a pre-compiled executable for this
                # exact aval signature — zero traces, zero compiles, own
                # metas; the key's jit entry stays warm as the fallback
                vfn, v_canon_out, v_metas = var
                try:
                    maybe_inject("compiled.segment", key=run[0].uid)
                    out_c = vfn(arrays)
                    out = {n: out_c[c] for n, c in v_canon_out.items()}
                    new_cols = {}
                    for n, (v, m) in out.items():
                        meta, kind = v_metas[n]
                        new_cols[n] = Column(kind, v, m, meta=meta)
                    return batch.with_columns(new_cols)
                except Exception as e:  # noqa: BLE001 — variants are an
                    # optimization: a rejected dispatch (aval drift the sig
                    # missed) falls through to the ordinary jit path below
                    record_failure("compiled", "degraded", e,
                                   point="compiled.aot",
                                   fallback="JIT recompile")
                    from .telemetry import REGISTRY
                    REGISTRY.counter("aot.fallback").inc()
                    self._aot_variants.pop((key, sig), None)
        jitted, canon_out_map = self._jitted[key]
        from .profiling import cost_analysis_enabled, record_program_cost
        if cost_analysis_enabled():
            record_program_cost("fused_transform", jitted, (arrays,))
        try:
            # chaos hook: an injected fault here exercises the eager-segment
            # demotion below, the same path a device dispatch failure takes
            maybe_inject("compiled.segment", key=run[0].uid)
            out_c = jitted(arrays)
            out = {n: out_c[c] for n, c in canon_out_map.items()}
        except _StageTraceError:
            self._jitted.pop(key, None)
            self._metas.pop(key, None)
            raise
        except Exception as e:  # noqa: BLE001
            if key in self._aot_installed:
                # the shipped executable rejected these inputs (shape/dtype
                # drift the ABI stamp could not see) — uninstall it and
                # retry on the ordinary jit path instead of going eager
                record_failure("compiled", "degraded", e,
                               point="compiled.aot",
                               fallback="JIT recompile")
                from .telemetry import REGISTRY
                REGISTRY.counter("aot.fallback").inc()
                self._aot_installed.discard(key)
                self._jitted.pop(key, None)
                self._metas.pop(key, None)
                return self._apply_run(batch, run, later, keep_intermediate)
            # unexpected jit-boundary failure: never break scoring — run the
            # segment eagerly (≙ apply_dag) and stop attempting to compile
            record_failure("compiled", "demoted", e,
                           point="compiled.segment",
                           stages=[st.uid for st in run],
                           fallback="eager per-stage execution")
            self._jitted.pop(key, None)
            self._metas.pop(key, None)
            self._demoted.update(st.uid for st in run)
            b = batch
            for st in run:
                b = st.transform_batch(b)
            return b
        metas_out = self._metas[key]
        new_cols = {}
        for n, (v, m) in out.items():
            meta, kind = metas_out[n]
            new_cols[n] = Column(kind, v, m, meta=meta)
        return batch.with_columns(new_cols)


def _kind_arrayish(kind) -> bool:
    """Static analog of Column.is_device for a feature kind: does a column of
    this kind hold dense arrays (vs host object arrays)?"""
    from .types import Geolocation, OPVector, Prediction, is_numeric_kind
    if kind is None:
        return False
    if issubclass(kind, (OPVector, Prediction, Geolocation)):
        return True
    if is_numeric_kind(kind):
        return True
    return False
