"""Packaged NLP model resources — the analog of the reference's `models`
module (models/src/main/resources/OpenNLP/*.bin, loaded lazily by
core/.../utils/text/OpenNLPModels.scala).

Where the reference ships OpenNLP binaries (NER/sentence/tokenizer/POS) and
Optimaize language profiles, this package ships JSON data files consumed by
the specialized text stages (ops/text_specialized.py):

  * ``lang_profiles.json``  — per-language stop-word profiles (67 languages
    across Latin/Cyrillic/Greek/Hebrew/Arabic/Indic scripts; script-sealed
    languages — zh-cn/zh-tw/ja/ko/th/km — are handled by Unicode script
    analysis in ops/text_specialized.py, ≙ the reference's 69-language enum
    at utils/.../text/LanguageDetector.scala:59)
    for LangDetector (≙ Optimaize profiles).
  * ``name_gender.json``    — first-name → gender dictionary for
    HumanNameDetector (≙ NameDetectUtils.DefaultGenderDictionary).
  * ``surnames.json``       — surname list (≙ DefaultNameDictionary).
  * ``honorifics.json``     — salutation tokens stripped in name parsing.

Resources load lazily and cache per-process, like OpenNLPModels' model cache.
"""

from __future__ import annotations

import functools
import json
import os
from typing import Any

_DIR = os.path.dirname(os.path.abspath(__file__))


@functools.lru_cache(maxsize=None)
def load_resource(name: str) -> Any:
    """Load + cache a packaged JSON resource by file name (≙
    OpenNLPModels.loadModel)."""
    path = os.path.join(_DIR, name)
    if not os.path.exists(path):
        raise FileNotFoundError(
            f"unknown resource {name!r}; available: "
            f"{sorted(f for f in os.listdir(_DIR) if f.endswith('.json'))}")
    with open(path, encoding="utf-8") as fh:
        return json.load(fh)


@functools.lru_cache(maxsize=None)
def lang_profiles() -> dict:
    """language → set of profile stop-words (cached: LangDetector consults
    this per row)."""
    return {k: set(v) for k, v in load_resource("lang_profiles.json").items()}


def gender_dictionary() -> dict:
    return dict(load_resource("name_gender.json"))


def name_dictionary() -> set:
    return set(load_resource("name_gender.json")) | set(
        load_resource("surnames.json"))


def honorifics() -> set:
    return set(load_resource("honorifics.json"))
