"""AOT-serialized executables: kill the cold-start compile wall.

PR 4 made recompiles cheap-ish (persistent XLA compile cache); this module
makes the serve path skip the compiler entirely.  At ``model.save()`` the
fused transform+scoring programs are warmed across the serving padding
ladder, lowered, compiled, and serialized
(``jax.experimental.serialize_executable``) into a per-platform
subdirectory of the bundle (``aot-cpu/``, ``aot-tpu/``, ...).  Every
artifact is digest-covered by the bundle MANIFEST, so corruption surfaces
as ``CorruptModelError`` before a byte of it reaches the runtime.  On
``WorkflowModel.load`` the executables deserialize straight into the
``ScoreProgram`` jit table — a fresh process scores its first record with
zero XLA compiles (asserted by ``scripts/ci_aot_smoke.py``).

Safety: XLA CPU executables bake in host ISA features (the SIGILL hazard
noted in ``__init__.py``) and TPU executables bake in the chip generation,
so every artifact carries an ABI stamp (platform, machine, jax version,
device count).  A mismatched stamp, an undeserializable payload, or a
shape/dtype drift at call time all fall back to the ordinary JIT path with
a ``degraded`` FailureLog note — AOT is an optimization, never a
correctness dependency.  Opt out with ``--no-aot`` / ``aotParams`` /
``TRANSMOGRIFAI_NO_AOT=1``.

The train-side half lives here too: ``pretrace_submit`` runs a family's
grid program through ``lower().compile()`` on a background thread while
transmogrification / fold prep still owns the main thread.  The compile
lands in the persistent cache, so the sweep's real fit call becomes a disk
hit and ``new_compiles_during_train`` collapses into otherwise-idle wall
time.  Estimators opt in via ``supports_pretrace`` (see models/base.py);
inside the pretrace scope their ``fit_arrays_grid`` only lowers+compiles —
it never executes, so sweep winners are bitwise unaffected.
"""

from __future__ import annotations

import contextlib
import io
import json
import os
import pickle
import platform as _platform
import threading
from typing import Any, Dict, List, Optional, Tuple

AOT_FORMAT_VERSION = 1
AOT_DIR_PREFIX = "aot-"
AOT_META_NAME = "aot.json"

# default ladder ceiling warmed/exported at save time; mirrors
# ScoringEngine's default max_batch so a default engine serves every
# padded batch size from shipped executables
_DEFAULT_LADDER_MAX = 64

_DISABLED = [False]          # process-level kill switch (--no-aot / params)


def set_aot_enabled(on: bool) -> None:
    _DISABLED[0] = not on


def aot_enabled() -> bool:
    if _DISABLED[0]:
        return False
    return os.environ.get("TRANSMOGRIFAI_NO_AOT", "0") in ("", "0")


def _count(name: str, n: int = 1) -> None:
    from .telemetry import REGISTRY
    REGISTRY.counter(name).inc(n)


# -- ABI stamp ---------------------------------------------------------------

def abi_stamp() -> Dict[str, Any]:
    """The compiling environment an executable is only valid in: XLA CPU
    payloads bake in host machine features, TPU payloads the chip
    generation, and jax pins the serialization format to its own version."""
    import jax
    return {
        "platform": jax.default_backend(),
        "machine": _platform.machine(),
        "jaxVersion": jax.__version__,
        "deviceCount": jax.device_count(),
    }


def abi_mismatch(stamp: Optional[Dict[str, Any]]) -> Optional[str]:
    """None when ``stamp`` matches the running process, else a short reason
    string naming the first mismatched field."""
    if not isinstance(stamp, dict):
        return "missing ABI stamp"
    here = abi_stamp()
    for field in ("platform", "machine", "jaxVersion", "deviceCount"):
        if stamp.get(field) != here[field]:
            return (f"{field} mismatch: bundle={stamp.get(field)!r} "
                    f"host={here[field]!r}")
    return None


# -- bundle export (save side) ----------------------------------------------

def _key_json(key: Tuple) -> Dict[str, Any]:
    uids, keep_intermediate, rows = key
    return {"uids": list(uids), "keepIntermediate": bool(keep_intermediate),
            "rows": int(rows)}


def _key_tuple(d: Dict[str, Any]) -> Tuple:
    return (tuple(d["uids"]), bool(d["keepIntermediate"]), int(d["rows"]))


def ladder_sizes(max_batch: int = _DEFAULT_LADDER_MAX) -> List[int]:
    from .serving.engine import _padding_ladder
    return _padding_ladder(max_batch)


def export_bundle(model, bundle_dir: str) -> int:
    """Warm ``model``'s score program across the serving padding ladder and
    serialize the resulting executables under
    ``<bundle_dir>/aot-<platform>/``.  Returns the number of executables
    written (0 disables nothing — a bundle without AOT artifacts simply
    loads on the JIT path).  Raises nothing: any failure is recorded as a
    swallowed FailureLog entry and the bundle ships without AOT."""
    from .resilience import record_failure
    if not aot_enabled():
        return 0
    try:
        return _export_bundle_inner(model, bundle_dir)
    except Exception as e:  # noqa: BLE001 — AOT is strictly optional
        record_failure("workflow.save", "swallowed", e,
                       point="checkpoint.aot",
                       detail="AOT export failed; bundle ships JIT-only")
        return 0


def _export_bundle_inner(model, bundle_dir: str) -> int:
    import jax
    from .resilience import record_failure
    from .serving.engine import records_to_batch
    from .telemetry import span

    program = model.score_program()
    max_batch = int(os.environ.get("TRANSMOGRIFAI_AOT_LADDER_MAX",
                                   _DEFAULT_LADDER_MAX))
    sizes = ladder_sizes(max_batch)
    with span("workflow.aot_export", sizes=sizes):
        # warm: score a synthetic record at every ladder size so the program
        # table holds exactly the serve-shaped entries (same monoid-zero
        # record ScoringEngine warms with).  These traces stay off the
        # global trace_count() books: a save() running concurrently with a
        # serving engine (lifecycle retrain+promote) must not land export
        # warmup traces inside the engine's online-trace window
        from .compiled import suppress_trace_count
        before = set(program._jitted)
        with suppress_trace_count():
            for size in sizes:
                try:
                    batch = records_to_batch(model.raw_features, [{}] * size)
                    model.score(batch=batch)
                except Exception as e:  # noqa: BLE001 — skip unwarmable sizes
                    record_failure("workflow.save", "swallowed", e,
                                   point="checkpoint.aot",
                                   detail=f"AOT warm at batch size {size}")
            # nnz-ladder warm (ISSUE 19): a sparse (hashed-text) frontier
            # column's flat-component shape is its nnz CAPACITY — the
            # monoid-zero records above only exercise the floor rung
            # (nnz=0 → cap 1024, which already serves every real batch with
            # ≤1024 entries).  Synthetic token records push the program
            # across higher nnz rungs so those serve with zero compiles
            # too.  Densities are tokens/record
            # (TRANSMOGRIFAI_AOT_NNZ_LADDER, comma-separated, "" disables);
            # models without text features skip — same records, same avals,
            # no new table entries.
            from .types import is_text_kind
            text_feats = [f for f in model.raw_features
                          if f.kind is not None and is_text_kind(f.kind)]
            densities = []
            for tok in os.environ.get("TRANSMOGRIFAI_AOT_NNZ_LADDER",
                                      "32").split(","):
                with contextlib.suppress(ValueError):
                    if int(tok) > 0:
                        densities.append(int(tok))
            for k_tok in densities if text_feats else []:
                text = " ".join(f"tok{j}" for j in range(k_tok))
                for size in sizes:
                    try:
                        recs = [{f.name: text for f in text_feats}
                                for _ in range(size)]
                        batch = records_to_batch(model.raw_features, recs)
                        model.score(batch=batch)
                    except Exception as e:  # noqa: BLE001
                        record_failure("workflow.save", "swallowed", e,
                                       point="checkpoint.aot",
                                       detail=f"AOT nnz warm at batch size "
                                              f"{size} x {k_tok} tokens")
        keys = [k for k in program._jitted
                if k in program._input_specs
                and (k in before or k[2] in sizes)]
        if not keys:
            return 0

        out_dir = os.path.join(bundle_dir,
                               AOT_DIR_PREFIX + jax.default_backend())
        os.makedirs(out_dir, exist_ok=True)
        index: List[Dict[str, Any]] = []
        written = 0
        # the export compiles must BYPASS the persistent compilation cache:
        # an executable jax re-loaded from the disk cache serializes with
        # its jitted fusion symbols missing ("Symbols not found" at
        # deserialize) — only a fresh backend compile round-trips
        pretrace_drain()
        # registry publish rides the same export loop: every executable the
        # bundle ships also lands in the fleet registry under its
        # family x rung key, so pool workers / tenants / CI on OTHER
        # bundles of the same content install instead of compiling
        from . import aot_registry
        family = (aot_registry.model_family_digest(bundle_dir)
                  if aot_registry.registry_enabled() else None)
        prev_cache = jax.config.jax_enable_compilation_cache
        jax.config.update("jax_enable_compilation_cache", False)
        try:
            for i, key in enumerate(sorted(keys,
                                           key=lambda k: (k[2], k[0]))):
                # aval variants (ISSUE 19): a key that saw more than one
                # input signature (sparse nnz rungs) exports one record per
                # signature; single-variant keys export the legacy record —
                # byte-compatible with pre-variant bundles
                variants = program._input_spec_variants.get(key) or {}
                if len(variants) > 1:
                    jobs = sorted(variants.items())
                else:
                    jobs = [(None, None)]
                for j, (sig, specs) in enumerate(jobs):
                    try:
                        rec = _serialize_key(program, key, specs=specs,
                                             sig=sig)
                        if not aot_registry.payload_roundtrips(rec):
                            # the executable came out of the persistent
                            # compile cache (its payload deserializes to
                            # "Symbols not found") — re-lower + re-compile
                            # once with every cache layer suspended so the
                            # bundle ships an installable build instead of
                            # silently skipping
                            _count("aot_registry.recompiles_for_publish")
                            with aot_registry.fresh_compile_env():
                                rec = _serialize_key(program, key,
                                                     specs=specs, sig=sig)
                            if not aot_registry.payload_roundtrips(rec):
                                raise RuntimeError(
                                    "payload does not deserialize even "
                                    "after a cache-suspended rebuild")
                    except Exception as e:  # noqa: BLE001 — best effort
                        record_failure("workflow.save", "swallowed", e,
                                       point="checkpoint.aot",
                                       detail=f"AOT serialize "
                                              f"rows={key[2]}")
                        continue
                    fname = (f"seg-{i:03d}.aotx" if sig is None
                             else f"seg-{i:03d}-v{j:02d}.aotx")
                    with open(os.path.join(out_dir, fname), "wb") as f:
                        f.write(rec)
                    ent = {"file": fname, **_key_json(key)}
                    if sig is not None:
                        ent["argSig"] = sig
                    index.append(ent)
                    written += 1
                    if family:
                        aot_registry.publish_score(family, key, program,
                                                   rec, specs=specs)
        finally:
            jax.config.update("jax_enable_compilation_cache", prev_cache)
        if family:
            program.registry_family = family
        if not written:
            # nothing serialized — drop the empty dir so the bundle stays
            # byte-identical to a JIT-only save
            with contextlib.suppress(OSError):
                os.rmdir(out_dir)
            return 0
        meta = {"formatVersion": AOT_FORMAT_VERSION, "abi": abi_stamp(),
                "executables": index}
        with open(os.path.join(out_dir, AOT_META_NAME), "w") as f:
            json.dump(meta, f, indent=2, sort_keys=True)
        _count("aot.executables_saved", written)
        return written


def _serialize_key(program, key: Tuple, specs: Any = None,
                   sig: Optional[str] = None) -> bytes:
    """Lower+compile+serialize one program-table entry.  ``specs``/``sig``
    select an aval VARIANT (ISSUE 19): sparse frontier columns put an
    nnz-capacity degree of freedom in the avals that the 3-field key cannot
    see, so multi-variant keys export one record per observed signature
    (tagged ``argSig``); single-variant keys stay byte-compatible with
    pre-variant bundles."""
    from jax.experimental.serialize_executable import serialize
    jitted, canon_out = program._jitted[key]
    if specs is None:
        specs = program._input_specs[key]
    compiled = jitted.lower(specs).compile()
    payload, in_tree, out_tree = serialize(compiled)
    rec = {
        "key": _key_json(key),
        "canonOut": dict(canon_out),
        "metas": dict(program._metas.get(key, {})),
        "payload": payload,
        "inTree": in_tree,
        "outTree": out_tree,
    }
    if sig is not None:
        rec["argSig"] = sig
    buf = io.BytesIO()
    pickle.dump(rec, buf, protocol=4)
    return buf.getvalue()


# -- bundle install (load side) ----------------------------------------------

def install_bundle(model, bundle_path: str) -> int:
    """Deserialize the bundle's AOT executables (if any, for this platform)
    into ``model``'s score program.  Returns the number installed.  Any
    mismatch or failure records a ``degraded`` note and leaves the model on
    the ordinary JIT path — never raises."""
    import glob

    from .resilience import record_failure
    if not aot_enabled():
        return 0

    def _fallback(reason: str, cause: Any = None) -> int:
        _count("aot.fallback")
        record_failure("checkpoint", "degraded",
                       cause if isinstance(cause, Exception) else reason,
                       point="checkpoint.aot", bundle=bundle_path,
                       fallback="JIT scoring path", detail=reason)
        return 0

    import jax
    here = AOT_DIR_PREFIX + jax.default_backend()
    aot_dir = os.path.join(bundle_path, here)
    if not os.path.isdir(aot_dir):
        others = [os.path.basename(d) for d in
                  glob.glob(os.path.join(bundle_path, AOT_DIR_PREFIX + "*"))
                  if os.path.isdir(d)]
        if others:
            return _fallback(
                f"bundle has AOT artifacts for {others}, none for {here}")
        return 0    # legacy / JIT-only bundle: nothing to do, nothing to log

    try:
        with open(os.path.join(aot_dir, AOT_META_NAME)) as f:
            meta = json.load(f)
    except Exception as e:  # noqa: BLE001
        return _fallback("unreadable aot.json", e)
    if meta.get("formatVersion", 0) > AOT_FORMAT_VERSION:
        return _fallback(
            f"AOT formatVersion {meta.get('formatVersion')} is newer than "
            f"supported {AOT_FORMAT_VERSION}")
    reason = abi_mismatch(meta.get("abi"))
    if reason is not None:
        return _fallback(f"ABI {reason}")

    import hashlib

    from .aot_registry import shared_load
    program = model.score_program()
    installed = 0
    for ent in meta.get("executables", []):
        fpath = os.path.join(aot_dir, ent.get("file", ""))
        try:
            with open(fpath, "rb") as f:
                raw = f.read()
            rec = pickle.loads(raw)
            # deserialize through the process-wide shared table keyed on
            # content: two tenants loading byte-identical bundles (same
            # family x rung) get ONE loaded executable and one copy of its
            # device memory
            fn = shared_load(hashlib.sha256(raw).hexdigest(), rec)
            program.install_executable(_key_tuple(rec["key"]), fn,
                                       rec["canonOut"], rec["metas"],
                                       sig=rec.get("argSig"))
            installed += 1
        except Exception as e:  # noqa: BLE001
            _fallback(f"undeserializable executable "
                      f"{ent.get('file')}", e)
    if installed:
        _count("aot.executables_loaded", installed)
    return installed


# -- concurrent pre-trace (train side) ---------------------------------------

_PRETRACE_TLS = threading.local()


def pretrace_mode() -> bool:
    """True on threads currently inside :func:`pretrace_scope` — estimator
    ``fit_arrays_grid`` implementations branch on this to lower+compile
    their grid programs without executing them."""
    return bool(getattr(_PRETRACE_TLS, "on", False))


@contextlib.contextmanager
def pretrace_scope():
    prev = getattr(_PRETRACE_TLS, "on", False)
    _PRETRACE_TLS.on = True
    try:
        yield
    finally:
        _PRETRACE_TLS.on = prev


# one background DAEMON thread: pre-traces queue behind each other (XLA's
# compiler is internally parallel; a single worker avoids oversubscribing
# the host while transmogrification / fold prep still owns the main
# thread), and a daemon never blocks interpreter exit on a slow compile
_POOL_LOCK = threading.Lock()
_QUEUE: "queue.Queue" = None  # type: ignore[assignment]
_IDLE = threading.Event()
_IDLE.set()


def pretrace_enabled() -> bool:
    """Pre-tracing pays a background compile so the foreground fit becomes a
    persistent-cache hit — without the cache it would literally double the
    compile bill, so it keys on the same env the fit-shape padding does.
    A configured executable registry also qualifies: its pre-trace pass can
    skip the compile entirely (deserialize a published executable) and its
    misses publish for the whole fleet."""
    if not aot_enabled():
        return False
    cache = os.environ.get("TRANSMOGRIFAI_COMPILE_CACHE", "")
    if bool(cache) and cache != "0":
        return True
    from .aot_registry import registry_enabled
    return registry_enabled()


def _pretrace_worker() -> None:
    from .resilience import record_failure
    while True:
        label, fn, failure_log = _QUEUE.get()
        try:
            try:
                with pretrace_scope():
                    fn()
                _count("aot.pretrace_compiled")
            except Exception as e:  # noqa: BLE001 — strictly advisory work
                _count("aot.pretrace_failed")
                # record into the SUBMITTER's log: the ambient thread-local
                # log does not cross into this worker thread
                if failure_log is not None:
                    failure_log.record("tuning", "swallowed", e,
                                       point="tuning.pretrace", detail=label)
                else:
                    record_failure("tuning", "swallowed", e,
                                   point="tuning.pretrace", detail=label)
        finally:
            _QUEUE.task_done()
            if _QUEUE.unfinished_tasks == 0:
                _IDLE.set()


def pretrace_submit(label: str, fn) -> None:
    """Run ``fn()`` (typically ``estimator.pretrace_arrays_grid(...)``) on
    the background pre-trace thread.  Failures are swallowed and counted —
    a missed pre-trace only costs the foreground compile it would have
    hidden."""
    global _QUEUE
    import queue

    from .resilience import active_failure_log
    with _POOL_LOCK:
        if _QUEUE is None:
            _QUEUE = queue.Queue()
            threading.Thread(target=_pretrace_worker, name="op-pretrace",
                             daemon=True).start()
        _count("aot.pretrace_submitted")
        _IDLE.clear()
        try:
            log = active_failure_log()
        except Exception:  # noqa: BLE001
            log = None
        _QUEUE.put((label, fn, log))


def pretrace_drain(timeout: Optional[float] = None) -> None:
    """Block until submitted pre-traces finish (tests / shutdown hygiene)."""
    _IDLE.wait(timeout)


def pretrace_shed() -> int:
    """Drop every QUEUED (not-yet-started) pre-trace — the RSS watchdog's
    soft-watermark shedder.  Pre-traces are strictly advisory (a dropped one
    only costs the foreground compile it would have hidden), so under host
    memory pressure they are the first load to go.  Returns the number of
    entries dropped (the watchdog logs it; exact bytes are unknowable before
    the compile runs)."""
    import queue

    with _POOL_LOCK:
        if _QUEUE is None:
            return 0
        dropped = 0
        while True:
            try:
                _QUEUE.get_nowait()
            except queue.Empty:
                break
            _QUEUE.task_done()
            dropped += 1
        if _QUEUE.unfinished_tasks == 0:
            _IDLE.set()
    if dropped:
        _count("aot.pretrace_shed", dropped)
    return dropped
