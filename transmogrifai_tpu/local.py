"""Local scoring — engine-free single-record serving (reference: the `local`
module, local/src/main/scala/com/salesforce/op/local/OpWorkflowModelLocal.scala:61-199,
score function at :93; MLeap replaced by direct row-level stage application —
our stages are their own runtime, no bundle conversion needed).

``score_function(model)`` returns a closure ``dict → dict`` that applies the
fitted DAG row-by-row with no batch engine involved: the TPU framework's
equivalent of Spark-free MLeap serving.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List

import numpy as np

from .columns import Column, ColumnBatch, column_from_values
from .stages.generator import FeatureGeneratorStage
from .types import FeatureType, Prediction


def extract_raw_value(feature, record: Dict[str, Any]) -> FeatureType:
    """Stage-0 raw extraction of one feature from one record
    (≙ FeatureGeneratorStage extract): apply the feature's extract_fn, then
    the monoid-zero rule for non-nullable kinds so unlabeled records score
    (the batch path's ``extract_column`` applies the same rule).  Shared by
    the row closure below and the serving engine's batch builder — parity
    between the two paths starts here."""
    gen = feature.origin_stage
    val = (gen.extract_fn(record)
           if isinstance(gen, FeatureGeneratorStage)
           else record.get(feature.name))
    if isinstance(val, FeatureType):
        return val
    if val is None and feature.kind.non_nullable:
        return feature.kind(0.0)  # monoid zero (unlabeled scoring)
    return feature.kind(val)


def score_function(workflow_model) -> Callable[[Dict[str, Any]], Dict[str, Any]]:
    """≙ OpWorkflowModelLocal.scoreFunction."""
    stages = workflow_model.stages
    raw_features = list(workflow_model.raw_features)
    result_names = {f.name for f in workflow_model.result_features}

    def score(record: Dict[str, Any]) -> Dict[str, Any]:
        # stage 0: raw extraction (≙ FeatureGeneratorStage extract)
        row: Dict[str, FeatureType] = {
            f.name: extract_raw_value(f, record) for f in raw_features}
        # fold the fitted transformer DAG row-wise (≙ transformKeyValue fold)
        for st in stages:
            out = st.transform_row(row)
            feats = st.output_features
            if isinstance(out, dict) and not isinstance(out, FeatureType):
                row.update(out)
            else:
                row[feats[0].name] = out
        result: Dict[str, Any] = {}
        for name in result_names:
            v = row.get(name)
            if isinstance(v, Prediction):
                result[name] = dict(v.value)
            elif isinstance(v, FeatureType):
                result[name] = v.value
            else:
                result[name] = v
        return result

    return score
