"""Local scoring — engine-free single-record serving (reference: the `local`
module, local/src/main/scala/com/salesforce/op/local/OpWorkflowModelLocal.scala:61-199,
score function at :93; MLeap replaced by direct row-level stage application —
our stages are their own runtime, no bundle conversion needed).

``score_function(model)`` returns a closure ``dict → dict`` that applies the
fitted DAG row-by-row with no batch engine involved: the TPU framework's
equivalent of Spark-free MLeap serving.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List

import numpy as np

from .columns import Column, ColumnBatch, column_from_values
from .stages.generator import FeatureGeneratorStage
from .types import FeatureType, Prediction


def score_function(workflow_model) -> Callable[[Dict[str, Any]], Dict[str, Any]]:
    """≙ OpWorkflowModelLocal.scoreFunction."""
    stages = workflow_model.stages
    raw_features = list(workflow_model.raw_features)
    result_names = {f.name for f in workflow_model.result_features}

    def score(record: Dict[str, Any]) -> Dict[str, Any]:
        # stage 0: raw extraction (≙ FeatureGeneratorStage extract)
        row: Dict[str, FeatureType] = {}
        for f in raw_features:
            gen = f.origin_stage
            val = (gen.extract_fn(record)
                   if isinstance(gen, FeatureGeneratorStage)
                   else record.get(f.name))
            if isinstance(val, FeatureType):
                row[f.name] = val
            elif val is None and f.kind.non_nullable:
                row[f.name] = f.kind(0.0)  # monoid zero (unlabeled scoring)
            else:
                row[f.name] = f.kind(val)
        # fold the fitted transformer DAG row-wise (≙ transformKeyValue fold)
        for st in stages:
            out = st.transform_row(row)
            feats = st.output_features
            if isinstance(out, dict) and not isinstance(out, FeatureType):
                row.update(out)
            else:
                row[feats[0].name] = out
        result: Dict[str, Any] = {}
        for name in result_names:
            v = row.get(name)
            if isinstance(v, Prediction):
                result[name] = dict(v.value)
            elif isinstance(v, FeatureType):
                result[name] = v.value
            else:
                result[name] = v
        return result

    return score
