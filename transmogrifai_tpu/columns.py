"""Columnar data representation — the TPU-native replacement for Spark
DataFrames (reference layer 0).

A ``ColumnBatch`` is an ordered mapping of feature name → ``Column``.  Numeric
columns live as dense device arrays plus a presence mask (``Option[T]`` →
(values, mask), cf. SURVEY.md §7.1); strings/lists/maps live host-side as numpy
object arrays until a fitted vectorizer lowers them to device arrays.  All
device-side stage transforms are pure functions over these arrays, so the whole
transform DAG jits into one XLA program.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, List, Optional, Sequence, Type

import numpy as np

from .types import (
    Binary, Date, DateList, DateTime, DateTimeList, FeatureType, Geolocation,
    Integral, MultiPickList, OPList, OPMap, OPNumeric, OPSet, OPVector,
    Prediction, Real, RealNN, Text, TextList, is_map_kind, is_numeric_kind,
    is_text_kind,
)
from .vector_meta import VectorMeta


_DEVICE_CACHE: Dict[int, Any] = {}   # id(host arr) → (weakref, device arr, lossless)
_DEVICE_CACHE_BYTES = [0]
# HBM the cache may pin (FIFO-evicted beyond this; override via env)
_DEVICE_CACHE_CAP = int(__import__("os").environ.get(
    "TRANSMOGRIFAI_DEVICE_CACHE_BYTES", 2 << 30))

# feature matrices at/above this element count store as bf16 on accelerators
_MATRIX_BF16_ELEMS = 1 << 26       # 64M elements = 256 MB in f32


def shed_device_cache() -> int:
    """Release every cached host→device transfer — the RSS watchdog's
    soft-watermark shedder.  The cache only saves re-transfers (columns are
    immutable; a dropped entry re-ships over the link on next use), so
    under host memory pressure its device bytes AND the host references
    pinning the source arrays go first.  Returns the bytes released."""
    released = _DEVICE_CACHE_BYTES[0]
    _DEVICE_CACHE.clear()
    _DEVICE_CACHE_BYTES[0] = 0
    return max(0, int(released))


def device_matrix(values):
    """Feature matrix for device compute: device-resident f32/bf16 arrays
    pass through untouched (bf16 is STORAGE — every consumer accumulates in
    f32, with the operand converts fused into its matmuls); anything else
    transfers via the f32 wire path."""
    import jax
    import jax.numpy as jnp

    if isinstance(values, jax.Array) and values.dtype in (jnp.float32,
                                                          jnp.bfloat16):
        return values
    return to_device_f32(values)


def feature_matrix_dtype(n_elems: int):
    """Storage dtype for a device-resident feature matrix of ``n_elems``.

    On accelerators, large matrices store as bf16 — the TPU-native
    storage/compute split (bf16 storage, f32 MXU accumulation): counts and
    one-hot indicators are exactly representable, real-valued features were
    already bf16-quantized by the host wire, and every downstream matmul
    upcasts its operands into f32 accumulation.  Halving residency is what
    lets two copies of a wide transmogrified matrix (raw + checked) coexist
    with the CV working set on a 16 GB chip.  Opt out with
    TRANSMOGRIFAI_MATRIX_F32=1; CPU backends always store f32."""
    import os

    import jax
    import jax.numpy as jnp

    if (n_elems >= _MATRIX_BF16_ELEMS
            and jax.default_backend() != "cpu"
            and os.environ.get("TRANSMOGRIFAI_MATRIX_F32") != "1"):
        return jnp.bfloat16
    return jnp.float32


def pack_bits(arr) -> np.ndarray:
    """Boolean/0-1 array → packed uint8 wire (8 rows per byte, little-endian
    bit order so the device unpack is a shift+mask)."""
    return np.packbits(np.asarray(arr).astype(bool).reshape(-1),
                       bitorder="little")


def unpack_bits_device(words, n: int, shape=None):
    """Device-side inverse of ``pack_bits`` → float32 0/1 array of ``n``
    elements (optionally reshaped).  Traceable."""
    import jax.numpy as jnp

    bits = (words[:, None].astype(jnp.int32)
            >> jnp.arange(8, dtype=jnp.int32)[None, :]) & 1
    flat = bits.reshape(-1)[:n].astype(jnp.float32)
    return flat if shape is None else flat.reshape(shape)


def to_device_f32(values, exact: bool = False) -> Any:
    """Host→device transfer of real-valued bulk data for compute.

    On accelerator backends the WIRE format is bf16 — half the bytes over the
    host link, which on tunneled TPU setups runs at single-digit MB/s and
    dominates ingestion wall time — while everything downstream accumulates in
    f32 on device (the standard TPU bf16-storage/f32-accumulate discipline).
    Exact for 0/1 masks and small integers; float features lose bits beyond
    bf16's 8-bit mantissa, which is noise relative to feature measurement
    error.  Opt out with TRANSMOGRIFAI_WIRE_F32=1.  CPU backends (tests,
    goldens) always transfer exact f32.

    ``exact=True`` marks value-critical data (sample/fold weights, labels):
    the bf16 wire is used only when it is verified lossless for the actual
    array contents (0/1 fold masks, small integers); otherwise the transfer
    falls back to exact f32.

    Large arrays are cached (weakref-keyed on the host buffer) so a column
    used by several stages — vectorizer fit, compiled transform, evaluate —
    ships over the link ONCE per batch rather than once per consumer.
    Columns are treated as immutable throughout the framework; in-place
    mutation of a transferred array is not supported.
    """
    import os
    import weakref

    import jax
    import jax.numpy as jnp

    if isinstance(values, jax.Array):
        return values if values.dtype == jnp.float32 else values.astype(
            jnp.float32)
    arr = np.asarray(values)
    big = arr.size >= (1 << 16) and arr.dtype in (np.float32, np.float64)
    if big:
        ent = _DEVICE_CACHE.get(id(arr))
        # a cached bf16-wire entry only satisfies an exact request when the
        # transfer was verified lossless at insertion time
        if ent is not None and ent[0]() is arr and (not exact or ent[2]):
            return ent[1]
    lossless = True
    use_bf16 = (big and jax.default_backend() != "cpu"
                and os.environ.get("TRANSMOGRIFAI_WIRE_F32") != "1")
    if use_bf16:
        import ml_dtypes
        wire = arr.astype(ml_dtypes.bfloat16)
        if exact:
            lossless = bool(np.array_equal(
                wire.astype(np.float32), arr.astype(np.float32)))
            use_bf16 = lossless
        else:
            lossless = False     # unverified; conservative for exact reuse
    if use_bf16:
        dev = jax.device_put(wire).astype(jnp.float32)
    else:
        lossless = True
        dev = jnp.asarray(arr, jnp.float32)
    if big:
        from .profiling import add_host_link_bytes
        add_host_link_bytes(wire.nbytes if use_bf16 else arr.size * 4)
        key = id(arr)
        nbytes = int(dev.size) * 4

        def _drop(_r, _k=key, _b=nbytes):
            if _DEVICE_CACHE.pop(_k, None) is not None:
                _DEVICE_CACHE_BYTES[0] -= _b

        try:
            ref = weakref.ref(arr, _drop)
        except TypeError:  # pragma: no cover — un-weakref-able array subtype
            return dev
        # replacing an entry (e.g. exact request over a cached lossy wire):
        # release the old bytes so the counter stays truthful
        prev = _DEVICE_CACHE.pop(key, None)
        if prev is not None:
            _DEVICE_CACHE_BYTES[0] -= int(prev[1].size) * 4
        while (_DEVICE_CACHE_BYTES[0] + nbytes > _DEVICE_CACHE_CAP
               and _DEVICE_CACHE):
            oldest = next(iter(_DEVICE_CACHE))   # dicts preserve insertion order
            _, old, _ = _DEVICE_CACHE.pop(oldest)
            _DEVICE_CACHE_BYTES[0] -= int(old.size) * 4
        _DEVICE_CACHE[key] = (ref, dev, lossless)
        _DEVICE_CACHE_BYTES[0] += nbytes
    return dev


@dataclass
class Column:
    """A typed column of N rows.

    Storage by kind:
      * numeric kinds   — ``values``: float32/int64 array [N]; ``mask``: bool [N]
                        (True = present).  RealNN/Prediction are mask-free.
      * text kinds      — ``values``: numpy object array [N] of str | None (host).
      * OPVector        — ``values``: float32 array [N, D]; ``meta``: VectorMeta.
      * Geolocation     — ``values``: float32 [N, 3]; ``mask``: bool [N].
      * lists/sets      — ``values``: numpy object array [N] of list/set (host).
      * maps            — ``values``: numpy object array [N] of dict (host).
      * Prediction      — ``values``: dict with 'prediction' [N] and optionally
                        'probability' [N, C], 'rawPrediction' [N, C] arrays.
    """

    kind: Type[FeatureType]
    values: Any
    mask: Optional[Any] = None
    meta: Optional[VectorMeta] = None

    def __len__(self) -> int:
        if isinstance(self.values, dict):
            return len(self.values["prediction"])
        return len(self.values)

    @property
    def is_device(self) -> bool:
        """True if values are dense arrays usable inside jit."""
        if isinstance(self.values, dict):
            return True
        return not (isinstance(self.values, np.ndarray) and self.values.dtype == object)

    def row_value(self, i: int) -> FeatureType:
        """Materialize row ``i`` as a typed value (local-scoring/test path)."""
        k = self.kind
        if k is Prediction or (isinstance(self.values, dict)):
            d = {"prediction": float(np.asarray(self.values["prediction"])[i])}
            for base in ("probability", "rawPrediction"):
                if base in self.values:
                    row = np.asarray(self.values[base])[i]
                    for j, v in enumerate(row):
                        d[f"{base}_{j}"] = float(v)
            return Prediction(d)
        if issubclass(k, OPVector):
            from .sparse.matrix import SparseMatrix
            if isinstance(self.values, SparseMatrix):
                return OPVector(list(self.values.dense_rows([i])[0].tolist()))
            return OPVector(list(np.asarray(self.values)[i].tolist()))
        if issubclass(k, Geolocation) and not self.is_host_object():
            if self.mask is not None and not bool(np.asarray(self.mask)[i]):
                return Geolocation()
            return Geolocation(list(np.asarray(self.values)[i].tolist()))
        if self.is_host_object():
            return k(self.values[i])
        v = np.asarray(self.values)[i]
        if self.mask is not None and not bool(np.asarray(self.mask)[i]):
            return k(None)
        if issubclass(k, (Integral,)):
            return k(int(v))
        if issubclass(k, Binary):
            return k(bool(v))
        return k(float(v))

    def is_host_object(self) -> bool:
        return isinstance(self.values, np.ndarray) and self.values.dtype == object


def _full_mask(n: int) -> np.ndarray:
    return np.ones(n, dtype=bool)


def indicator_2d(flags: Iterable) -> np.ndarray:
    """[N, 1] float32 indicator block from truthy flags — shape-safe at N==0
    (a list-comprehension ``np.array([[1.0] if ...])`` collapses to shape (0,)
    on empty input and breaks axis-1 concatenation)."""
    arr = np.fromiter((1.0 if f else 0.0 for f in flags), np.float32)
    return arr.reshape(-1, 1)


def numeric_column(kind: Type[FeatureType], values: Iterable, n: Optional[int] = None) -> Column:
    """Build a numeric column from python values with Nones.

    A value the kind cannot coerce raises a ``ValueError`` naming the kind,
    the offending row and the value (with ``violation_kind`` set to the
    quality.py taxonomy), so a poison record in a batch is attributable to
    its row instead of surfacing as a bare ``float()`` traceback."""
    vals = list(values)
    n = len(vals) if n is None else n
    mask = np.array([v is not None for v in vals], dtype=bool)
    if issubclass(kind, (Date, DateTime)) or issubclass(kind, Integral):
        cast, zero, dtype = int, 0, np.int64
    elif issubclass(kind, Binary):
        cast, zero, dtype = bool, False, bool
    else:
        cast, zero, dtype = float, np.nan, np.float32
    try:
        arr = np.array([zero if v is None else cast(v) for v in vals],
                       dtype=dtype)
    except (TypeError, ValueError) as e:
        bad_row = None
        for i, v in enumerate(vals):
            if v is None:
                continue
            try:
                cast(v)
            except (TypeError, ValueError):
                bad_row = i
                break
        err = ValueError(
            f"{kind.__name__} column: non-coercible value at row "
            f"{bad_row}: {str(vals[bad_row])[:60]!r}" if bad_row is not None
            else f"{kind.__name__} column: non-coercible value ({e})")
        err.violation_kind = "NonCoercibleValue"  # quality.py taxonomy
        raise err from e
    if kind.non_nullable and not mask.all():
        bad = int((~mask).sum())
        err = ValueError(f"{kind.__name__} column has {bad} empty values")
        err.violation_kind = "MissingRequiredField"  # quality.py taxonomy
        raise err
    return Column(kind, arr, mask=None if kind.non_nullable else mask)


def text_column(kind: Type[FeatureType], values: Iterable) -> Column:
    arr = np.array([None if v is None or v == "" else str(v) for v in values], dtype=object)
    return Column(kind, arr)


def object_column(kind: Type[FeatureType], values: Iterable) -> Column:
    return Column(kind, np.array(list(values) + [None], dtype=object)[:-1])


def vector_column(values, meta: VectorMeta) -> Column:
    return Column(OPVector, values, meta=meta)


def column_from_values(kind: Type[FeatureType], values: Iterable) -> Column:
    """Dispatch on kind to build the right storage."""
    if is_numeric_kind(kind):
        return numeric_column(kind, values)
    if is_text_kind(kind):
        return text_column(kind, values)
    if issubclass(kind, OPVector):
        vals = [np.asarray(v.value if isinstance(v, OPVector) else v,
                           dtype=np.float32)
                for v in values if v is not None and not (
                    isinstance(v, (list, tuple)) and len(v) == 0)]
        rows = list(values)
        dim = len(vals[0]) if vals else 0
        arr = np.zeros((len(rows), dim), dtype=np.float32)
        for i, v in enumerate(rows):
            data = v.value if isinstance(v, OPVector) else v
            if data is None or len(data) == 0:
                continue  # missing vector → zero row (lenient, like fills)
            arr[i, :] = np.asarray(data, dtype=np.float32)
        return Column(OPVector, arr)
    return object_column(kind, values)


class ColumnBatch:
    """Ordered name → Column mapping; the working set of a workflow run."""

    def __init__(self, columns: Optional[Dict[str, Column]] = None, length: Optional[int] = None):
        self._cols: Dict[str, Column] = dict(columns or {})
        self._length = length
        if self._length is None and self._cols:
            self._length = len(next(iter(self._cols.values())))

    def __len__(self) -> int:
        return self._length or 0

    def __contains__(self, name: str) -> bool:
        return name in self._cols

    def __getitem__(self, name: str) -> Column:
        return self._cols[name]

    def get(self, name: str) -> Optional[Column]:
        return self._cols.get(name)

    def names(self) -> List[str]:
        return list(self._cols)

    def items(self):
        return self._cols.items()

    def with_column(self, name: str, col: Column) -> "ColumnBatch":
        new = dict(self._cols)
        new[name] = col
        return ColumnBatch(new, self._length if self._length is not None else len(col))

    def with_columns(self, cols: Dict[str, Column]) -> "ColumnBatch":
        new = dict(self._cols)
        new.update(cols)
        n = self._length
        if n is None and cols:
            n = len(next(iter(cols.values())))
        return ColumnBatch(new, n)

    def select(self, names: Sequence[str]) -> "ColumnBatch":
        return ColumnBatch({n: self._cols[n] for n in names}, self._length)

    def drop(self, names: Sequence[str]) -> "ColumnBatch":
        drop = set(names)
        return ColumnBatch({n: c for n, c in self._cols.items() if n not in drop}, self._length)

    def take_rows(self, idx: np.ndarray) -> "ColumnBatch":
        """Row subset (host-side gather; used by splitters/CV on small data)."""
        from .sparse.matrix import SparseMatrix
        out: Dict[str, Column] = {}
        for name, c in self._cols.items():
            if isinstance(c.values, dict):
                vals = {k: np.asarray(v)[idx] for k, v in c.values.items()}
            elif isinstance(c.values, SparseMatrix):
                vals = c.values.take_rows(idx)   # stays sparse end-to-end
            else:
                vals = np.asarray(c.values)[idx]
            mask = None if c.mask is None else np.asarray(c.mask)[idx]
            out[name] = Column(c.kind, vals, mask=mask, meta=c.meta)
        return ColumnBatch(out, int(len(idx)))

    def row(self, i: int) -> Dict[str, FeatureType]:
        return {name: c.row_value(i) for name, c in self._cols.items()}
