"""Sparse device representation for high-cardinality hashed features.

The dense transmogrification path materializes a ``[N, num_hashes]``
feature matrix; at 100k+ hashed columns that matrix dominates memory even
though almost every cell is zero.  This package provides the second device
data representation the rest of the pipeline threads through:

- :mod:`transmogrifai_tpu.sparse.matrix` — ``SparseMatrix``, a padded
  flat-COO container whose nnz capacity and row count sit on the same
  zero-pad size ladders as the dense path, so fitted executables replay
  from the persistent compile cache across batches.
- :mod:`transmogrifai_tpu.sparse.transform` — the fused
  ``hash_tokens_flat`` → device sparse matrix transform (the dense
  ``[N, num_hashes]`` array is never materialized), plus process-wide
  nnz/density stats feeding the telemetry gauges.
"""

from transmogrifai_tpu.sparse.matrix import (  # noqa: F401
    SparseMatrix,
    nnz_capacity,
    sp_matmat,
    sp_matvec,
    sp_rmatmat,
    sp_rmatvec,
)
from transmogrifai_tpu.sparse.transform import (  # noqa: F401
    combine_blocks,
    hash_tokens_to_sparse,
    record_sparse_stats,
    reset_sparse_stats,
    sparse_from_hash_flat,
    sparse_stats,
)

__all__ = [
    "SparseMatrix",
    "nnz_capacity",
    "sp_matvec",
    "sp_rmatvec",
    "sp_matmat",
    "sp_rmatmat",
    "sparse_from_hash_flat",
    "hash_tokens_to_sparse",
    "combine_blocks",
    "sparse_stats",
    "record_sparse_stats",
    "reset_sparse_stats",
]
