"""Padded flat-COO device container for hashed-text feature matrices.

Layout: three flat device arrays — ``values [cap] f32``, ``indices [cap]
int32`` (column ids) and ``row_ids [cap] int32`` — where the first ``nnz``
entries are real and the remainder is padding (``value 0.0`` at
``row 0 / col 0``, which contributes nothing to any segment sum).  The
entry capacity sits on the same {2^k, 1.5*2^k} size ladder as the dense
batch ladder, and the row count can be padded with empty rows, so the
fitted/scoring executables specialize on a small set of shapes and replay
from the persistent compile cache across batches.

This is COO rather than row-pointer CSR because every consumer is a
gather/segment-sum (`matvec`, `rmatvec`, column moments): with
``num_segments`` static, XLA lowers those to a single sorted scatter-add
and no kernel ever needs ``row_ptr``.  ``row_ids`` is also what keeps the
pad semantics trivial — a pad entry is just a zero addend.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

_NNZ_FLOOR = 1024


def nnz_capacity(n, floor=_NNZ_FLOOR):
    """Smallest ladder rung {2^k, 1.5*2^k} >= n, with a floor.

    Mirrors the dense batch ladder so sparse executables enjoy the same
    compile-cache replay guarantees.
    """
    n = max(int(n), 1)
    cap = floor
    while cap < n:
        if (cap * 3) // 2 >= n:
            return (cap * 3) // 2
        cap *= 2
    return cap


@functools.partial(jax.jit, static_argnames=("n_rows",))
def sp_matvec(values, indices, row_ids, v, *, n_rows):
    """``X @ v`` for flat-COO ``X`` — [cap] entries -> [n_rows]."""
    return jax.ops.segment_sum(values * jnp.take(v, indices),
                               row_ids, num_segments=n_rows)


@functools.partial(jax.jit, static_argnames=("n_cols",))
def sp_rmatvec(values, indices, row_ids, u, *, n_cols):
    """``X.T @ u`` for flat-COO ``X`` — [cap] entries -> [n_cols]."""
    return jax.ops.segment_sum(values * jnp.take(u, row_ids),
                               indices, num_segments=n_cols)


@functools.partial(jax.jit, static_argnames=("n_rows",))
def sp_matmat(values, indices, row_ids, m, *, n_rows):
    """``X @ M`` for flat-COO ``X`` and dense ``M [n_cols, k]`` -> [n_rows, k]."""
    prod = values[:, None] * jnp.take(m, indices, axis=0)
    return jax.ops.segment_sum(prod, row_ids, num_segments=n_rows)


@functools.partial(jax.jit, static_argnames=("n_cols",))
def sp_rmatmat(values, indices, row_ids, g, *, n_cols):
    """``X.T @ G`` for flat-COO ``X`` and dense ``G [n_rows, k]`` -> [n_cols, k]."""
    prod = values[:, None] * jnp.take(g, row_ids, axis=0)
    return jax.ops.segment_sum(prod, indices, num_segments=n_cols)


def _concat_ranges(starts, counts):
    """Vectorized ``concatenate([arange(s, s+c) for s, c in ...])``."""
    counts = np.asarray(counts, dtype=np.int64)
    total = int(counts.sum())
    if total == 0:
        return np.zeros(0, dtype=np.int64)
    nz = counts > 0
    s, c = np.asarray(starts, dtype=np.int64)[nz], counts[nz]
    out = np.ones(total, dtype=np.int64)
    out[0] = s[0]
    if len(s) > 1:
        cum = np.cumsum(c)[:-1]
        out[cum] = s[1:] - (s[:-1] + c[:-1] - 1)
    return np.cumsum(out)


class SparseMatrix:
    """Device-resident padded flat-COO matrix (see module docstring)."""

    __slots__ = ("values", "indices", "row_ids", "n_rows", "n_cols", "nnz",
                 "__weakref__")

    def __init__(self, values, indices, row_ids, n_rows, n_cols, nnz=None):
        self.values = jnp.asarray(values, dtype=jnp.float32)
        self.indices = jnp.asarray(indices, dtype=jnp.int32)
        self.row_ids = jnp.asarray(row_ids, dtype=jnp.int32)
        self.n_rows = int(n_rows)
        self.n_cols = int(n_cols)
        self.nnz = int(self.values.shape[0] if nnz is None else nnz)
        if not (self.values.shape == self.indices.shape == self.row_ids.shape):
            raise ValueError("values/indices/row_ids must share one flat shape")

    # ---- shape protocol (what the dense code paths probe) -------------
    @property
    def shape(self):
        return (self.n_rows, self.n_cols)

    @property
    def ndim(self):
        return 2

    @property
    def dtype(self):
        return self.values.dtype

    @property
    def capacity(self):
        return int(self.values.shape[0])

    @property
    def density(self):
        cells = self.n_rows * self.n_cols
        return float(self.nnz) / cells if cells else 0.0

    @property
    def nbytes(self):
        return int(self.values.nbytes + self.indices.nbytes
                   + self.row_ids.nbytes)

    def __len__(self):
        return self.n_rows

    def __repr__(self):
        return (f"SparseMatrix(shape={self.shape}, nnz={self.nnz}, "
                f"capacity={self.capacity}, density={self.density:.2e})")

    def __array__(self, dtype=None, copy=None):
        raise TypeError(
            "refusing to densify SparseMatrix implicitly "
            f"(shape {self.shape}); call .to_dense() explicitly")

    # ---- construction -------------------------------------------------
    @classmethod
    def from_coo(cls, rows, cols, vals, n_rows, n_cols, nnz_pad=None):
        """Build from host COO triples; pads entry count to the ladder."""
        rows = np.asarray(rows, dtype=np.int32)
        cols = np.asarray(cols, dtype=np.int32)
        vals = np.asarray(vals, dtype=np.float32)
        nnz = len(vals)
        cap = nnz_capacity(nnz) if nnz_pad is None else int(nnz_pad)
        if cap < nnz:
            raise ValueError(f"nnz_pad {cap} < nnz {nnz}")
        if cap > nnz:
            pad = cap - nnz
            rows = np.concatenate([rows, np.zeros(pad, np.int32)])
            cols = np.concatenate([cols, np.zeros(pad, np.int32)])
            vals = np.concatenate([vals, np.zeros(pad, np.float32)])
        return cls(vals, cols, rows, n_rows, n_cols, nnz=nnz)

    @classmethod
    def from_dense(cls, x, nnz_pad=None):
        """Test/interop helper: dense [N, D] -> SparseMatrix."""
        x = np.asarray(x, dtype=np.float32)
        rows, cols = np.nonzero(x)
        return cls.from_coo(rows, cols, x[rows, cols], x.shape[0],
                            x.shape[1], nnz_pad=nnz_pad)

    # ---- device linear algebra ----------------------------------------
    def matvec(self, v):
        return sp_matvec(self.values, self.indices, self.row_ids,
                         jnp.asarray(v), n_rows=self.n_rows)

    def rmatvec(self, u):
        return sp_rmatvec(self.values, self.indices, self.row_ids,
                          jnp.asarray(u), n_cols=self.n_cols)

    def matmat(self, m):
        return sp_matmat(self.values, self.indices, self.row_ids,
                         jnp.asarray(m), n_rows=self.n_rows)

    def __matmul__(self, other):
        other = jnp.asarray(other)
        if other.ndim == 1:
            return self.matvec(other)
        return self.matmat(other)

    def to_dense(self):
        """Materialize the dense [n_rows, n_cols] matrix (tests/small data)."""
        out = jnp.zeros((self.n_rows, self.n_cols), dtype=self.values.dtype)
        return out.at[self.row_ids, self.indices].add(self.values)

    # ---- padding / slicing (ladder semantics) -------------------------
    def pad_rows(self, n_rows):
        """Grow to ``n_rows`` with empty rows (exact: pads own no entries)."""
        if n_rows < self.n_rows:
            raise ValueError(f"pad_rows {n_rows} < n_rows {self.n_rows}")
        if n_rows == self.n_rows:
            return self
        return SparseMatrix(self.values, self.indices, self.row_ids,
                            n_rows, self.n_cols, nnz=self.nnz)

    def host_coo(self):
        """Real (unpadded) entries as host numpy (rows, cols, vals)."""
        k = self.nnz
        return (np.asarray(self.row_ids[:k]), np.asarray(self.indices[:k]),
                np.asarray(self.values[:k]))

    def take_rows(self, idx):
        """Row-subset (duplicates allowed) -> new SparseMatrix."""
        idx = np.asarray(idx, dtype=np.int64)
        rows, cols, vals = self.host_coo()
        order = np.argsort(rows, kind="stable")
        rows, cols, vals = rows[order], cols[order], vals[order]
        starts = np.searchsorted(rows, idx, side="left")
        ends = np.searchsorted(rows, idx, side="right")
        counts = ends - starts
        gather = _concat_ranges(starts, counts)
        out_rows = np.repeat(np.arange(len(idx), dtype=np.int64), counts)
        return SparseMatrix.from_coo(out_rows, cols[gather], vals[gather],
                                     len(idx), self.n_cols)

    def dense_rows(self, idx):
        """Densify a small row subset as host numpy [len(idx), n_cols]."""
        sub = self.take_rows(idx)
        return np.asarray(sub.to_dense())


# pytree registration lets a SparseMatrix cross jit boundaries (compiled
# scoring passes one as a fused-program argument) and ride vmap/grad with the
# COO arrays as leaves.  ``nnz`` is deliberately NOT aux data: it varies per
# batch while the padded capacity sits on the ladder, and keying the jit
# cache on it would retrace every batch.  A reconstructed matrix therefore
# reports nnz == capacity — exact for all device math (padding is zero
# entries), only host_coo/density on a rebuilt object over-count the pad.
def _sm_flatten(sm):
    return (sm.values, sm.indices, sm.row_ids), (sm.n_rows, sm.n_cols)


def _sm_unflatten(aux, leaves):
    values, indices, row_ids = leaves
    sm = object.__new__(SparseMatrix)
    sm.values, sm.indices, sm.row_ids = values, indices, row_ids
    sm.n_rows, sm.n_cols = aux
    sm.nnz = int(getattr(values, "shape", (0,))[0] or 0)
    return sm


jax.tree_util.register_pytree_node(SparseMatrix, _sm_flatten, _sm_unflatten)
