"""Fused hashed-text -> device sparse matrix transform.

``ops.text.hash_tokens_flat`` already produces the flat bucket stream
``(lens [N], flat [total_tokens])`` on the host.  The dense path scatters
that stream into a ``[N, num_hashes]`` count matrix; here we instead
deduplicate ``(row, bucket)`` pairs on the host (one ``np.unique`` over
int64 keys — O(tokens log tokens), no ``num_hashes``-sized allocation
anywhere) and ship the COO triples to the device as a
:class:`~transmogrifai_tpu.sparse.matrix.SparseMatrix`.  Peak memory is
O(nnz), independent of ``num_hashes``.

Also home to the process-wide sparse stats behind the
``sparse.nnz_total`` / ``sparse.density`` telemetry gauges.
"""

from __future__ import annotations

import threading

import numpy as np

from transmogrifai_tpu.sparse.matrix import SparseMatrix

_LOCK = threading.Lock()
_STATS = {"nnz_total": 0, "cells_total": 0, "matrices": 0, "density": 0.0}


def record_sparse_stats(sm):
    """Fold one built matrix into the process-wide sparse gauges."""
    with _LOCK:
        _STATS["nnz_total"] += int(sm.nnz)
        _STATS["cells_total"] += int(sm.n_rows) * int(sm.n_cols)
        _STATS["matrices"] += 1
        _STATS["density"] = float(sm.density)


def sparse_stats():
    """Snapshot: cumulative nnz/cells plus the last-built matrix density."""
    with _LOCK:
        return dict(_STATS)


def reset_sparse_stats():
    with _LOCK:
        _STATS.update(nnz_total=0, cells_total=0, matrices=0, density=0.0)


def sparse_from_hash_flat(lens, flat, num_hashes, *, binary=False,
                          row_pad=None, nnz_pad=None, col_offset=0,
                          n_cols=None, record=True):
    """Flat hashed-bucket stream -> deduplicated device SparseMatrix.

    ``lens [N] int`` is tokens-per-row, ``flat [sum(lens)] int`` the bucket
    ids.  Duplicate ``(row, bucket)`` hits either count (``binary=False``)
    or collapse to 1.0 (``binary=True``).  Empty-token rows simply own no
    entries — no dense intermediate exists for any row shape.
    """
    lens = np.asarray(lens, dtype=np.int64)
    flat = np.asarray(flat, dtype=np.int64)
    n = len(lens)
    rows = np.repeat(np.arange(n, dtype=np.int64), lens)
    # one int64 key per token: dedupe (row, bucket) in a single unique()
    keys, counts = np.unique(rows * num_hashes + flat, return_counts=True)
    out_rows = keys // num_hashes
    out_cols = keys % num_hashes + col_offset
    vals = (np.ones(len(keys), dtype=np.float32) if binary
            else counts.astype(np.float32))
    sm = SparseMatrix.from_coo(out_rows, out_cols, vals, n,
                               num_hashes if n_cols is None else n_cols,
                               nnz_pad=nnz_pad)
    if row_pad is not None:
        sm = sm.pad_rows(row_pad)
    if record:
        record_sparse_stats(sm)
    return sm


def hash_tokens_to_sparse(token_lists, num_hashes, *, binary=False,
                          row_pad=None, nnz_pad=None):
    """Tokenized rows -> device SparseMatrix via the shared FNV-1a hasher."""
    from transmogrifai_tpu.ops.text import hash_tokens_flat
    lens, flat = hash_tokens_flat(token_lists, num_hashes)
    return sparse_from_hash_flat(lens, flat, num_hashes, binary=binary,
                                 row_pad=row_pad, nnz_pad=nnz_pad)


def combine_blocks(blocks, n_rows, *, record=True):
    """Horizontally stack feature blocks into one SparseMatrix.

    ``blocks`` is a list of either ``SparseMatrix`` or dense host/device
    ``[n_rows, w]`` blocks (dense blocks contribute their nonzero cells —
    exact for every linear consumer).  Column offsets follow block order,
    matching the dense ``VectorsCombiner`` concat layout.
    """
    if (len(blocks) == 1 and isinstance(blocks[0], SparseMatrix)
            and blocks[0].n_rows == n_rows):
        # single sparse block: no host COO roundtrip, and — because nothing
        # here touches entry VALUES — the combine stays jit-traceable, so
        # the compiled score path can fuse combiner + model forward
        if record:
            record_sparse_stats(blocks[0])
        return blocks[0]
    rows_all, cols_all, vals_all = [], [], []
    offset = 0
    for blk in blocks:
        if isinstance(blk, SparseMatrix):
            if blk.n_rows != n_rows:
                raise ValueError(
                    f"block rows {blk.n_rows} != batch rows {n_rows}")
            r, c, v = blk.host_coo()
            rows_all.append(r.astype(np.int64))
            cols_all.append(c.astype(np.int64) + offset)
            vals_all.append(v)
            offset += blk.n_cols
        else:
            arr = np.asarray(blk, dtype=np.float32)
            if arr.ndim == 1:
                arr = arr[:, None]
            if arr.shape[0] != n_rows:
                raise ValueError(
                    f"block rows {arr.shape[0]} != batch rows {n_rows}")
            r, c = np.nonzero(arr)
            rows_all.append(r.astype(np.int64))
            cols_all.append(c.astype(np.int64) + offset)
            vals_all.append(arr[r, c])
            offset += arr.shape[1]
    if not rows_all:
        return SparseMatrix.from_coo([], [], [], n_rows, 0)
    sm = SparseMatrix.from_coo(np.concatenate(rows_all),
                               np.concatenate(cols_all),
                               np.concatenate(vals_all), n_rows, offset)
    if record:
        record_sparse_stats(sm)
    return sm
