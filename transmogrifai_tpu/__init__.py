"""transmogrifai_tpu — a TPU-native AutoML framework for structured data.

A from-scratch re-design of Salesforce TransmogrifAI (Scala/Spark) on JAX/XLA:
typed features with lineage, a compiled stage DAG, automatic per-type
vectorization, sanity checking / leakage detection, cross-validated model
selection over linear and tree-ensemble models trained data-parallel on the
TPU mesh, evaluators, model insights, and a serializable workflow model.
"""

from . import types
from .aggregators import CustomMonoidAggregator, MonoidAggregator
from .columns import Column, ColumnBatch
from .features import Feature, FeatureBuilder, features_from_schema
from .stages import (Estimator, FeatureGeneratorStage, PipelineStage,
                     Transformer, TransformerModel)
from .vector_meta import VectorColumnMeta, VectorMeta

__version__ = "0.1.0"

__all__ = [
    "types", "Column", "ColumnBatch", "Feature", "FeatureBuilder",
    "features_from_schema", "PipelineStage", "Transformer", "Estimator",
    "TransformerModel", "FeatureGeneratorStage", "VectorMeta",
    "VectorColumnMeta", "MonoidAggregator", "CustomMonoidAggregator",
]


def __getattr__(name):
    # Lazy imports of heavier submodules to keep `import transmogrifai_tpu` fast.
    if name in ("Workflow", "WorkflowModel"):
        from .workflow import Workflow, WorkflowModel
        return {"Workflow": Workflow, "WorkflowModel": WorkflowModel}[name]
    if name in ("BinaryClassificationModelSelector",
                "MultiClassificationModelSelector", "RegressionModelSelector"):
        from . import selector
        return getattr(selector, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
