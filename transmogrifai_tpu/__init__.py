"""transmogrifai_tpu — a TPU-native AutoML framework for structured data.

A from-scratch re-design of Salesforce TransmogrifAI (Scala/Spark) on JAX/XLA:
typed features with lineage, a compiled stage DAG, automatic per-type
vectorization, sanity checking / leakage detection, cross-validated model
selection over linear and tree-ensemble models trained data-parallel on the
TPU mesh, evaluators, model insights, and a serializable workflow model.
"""

import os as _os

# Persistent XLA compilation cache: fitted-grid / tree programs are large and
# their compiles dominate cold-start wall time; caching them on disk makes
# every run after the first pay execution cost only (the TPU analog of the
# JVM/Spark warm-start the reference relies on).
#
# TRANSMOGRIFAI_COMPILE_CACHE=<dir> pins the cache root explicitly (scoped
# per backend platform underneath) and caches EVERY program, so a warm
# process reports ~0 new compiles; =0 disables the cache outright.  Unset,
# the legacy default applies: /tmp/transmogrifai_tpu_jax_cache_<plat> with a
# 0.1s floor, opt out with TRANSMOGRIFAI_COMPILATION_CACHE=0.
_cc = _os.environ.get("TRANSMOGRIFAI_COMPILE_CACHE")
if _cc != "0" and (_cc or _os.environ.get(
        "TRANSMOGRIFAI_COMPILATION_CACHE", "1") != "0"):
    try:
        import jax as _jax

        # Scope the cache per backend platform: CPU AOT entries carry host
        # machine-feature assumptions, and a cache populated by an
        # accelerator-process's host compiler must not be loaded by a pure
        # CPU process (xla cpu_aot_loader rejects them with SIGILL warnings).
        _plat = ((_os.environ.get("JAX_PLATFORMS") or "default")
                 .split(",")[0].strip() or "default")
        if _cc:
            _jax.config.update("jax_compilation_cache_dir",
                               _os.path.join(_cc, _plat))
            _jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 0.0)
        else:
            _jax.config.update(
                "jax_compilation_cache_dir",
                _os.environ.get("JAX_COMPILATION_CACHE_DIR",
                                f"/tmp/transmogrifai_tpu_jax_cache_{_plat}"))
            # cache even small programs: a warm train run launches ~90
            # distinct executables and re-compiling the sub-second ones
            # still costs multiple seconds of wall per run
            _jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 0.1)
    except Exception:  # pragma: no cover — cache is best-effort
        pass

# compile-vs-execute counters (profiling.compile_stats) ride jax.monitoring's
# process-global listeners; registering costs nothing until a compile fires
try:
    from .profiling import install_compile_listeners as _icl
    _icl()
except Exception:  # pragma: no cover — diagnostics only
    pass

from . import types
from .aggregators import CustomMonoidAggregator, MonoidAggregator
from .columns import Column, ColumnBatch
from .features import Feature, FeatureBuilder, features_from_schema
from .stages import (Estimator, FeatureGeneratorStage, PipelineStage,
                     Transformer, TransformerModel)
from .vector_meta import VectorColumnMeta, VectorMeta

__version__ = "0.1.0"

__all__ = [
    "types", "Column", "ColumnBatch", "Feature", "FeatureBuilder",
    "features_from_schema", "PipelineStage", "Transformer", "Estimator",
    "TransformerModel", "FeatureGeneratorStage", "VectorMeta",
    "VectorColumnMeta", "MonoidAggregator", "CustomMonoidAggregator",
    # lazy (heavy) exports, see __getattr__:
    "Workflow", "WorkflowModel", "BinaryClassificationModelSelector",
    "MultiClassificationModelSelector", "RegressionModelSelector",
    "Evaluators", "OpParams", "OpWorkflowRunner", "OpApp", "RunType",
    "ModelInsights", "RecordInsightsLOCO", "RecordInsightsCorr",
    "RawFeatureFilter",
    "score_function", "transmogrify",
    "RetryPolicy", "FailureLog", "FaultInjector", "InjectedFault",
    "WatchdogTimeout", "AllCandidatesFailed", "run_with_deadline",
    "use_failure_log", "inject_faults",
    "CheckpointError", "CorruptModelError", "ModelVersionError",
    "TrainingPreempted", "SweepCheckpoint", "verify_bundle",
    "atomic_bundle_write", "preemption_guard", "shutdown_requested",
    "Tracer", "use_tracer", "active_tracer", "span", "current_span_id",
    "MetricsRegistry", "telemetry_summary",
]

_LAZY = {
    "Workflow": ("workflow", "Workflow"),
    "WorkflowModel": ("workflow", "WorkflowModel"),
    "BinaryClassificationModelSelector": ("selector", "BinaryClassificationModelSelector"),
    "MultiClassificationModelSelector": ("selector", "MultiClassificationModelSelector"),
    "RegressionModelSelector": ("selector", "RegressionModelSelector"),
    "Evaluators": ("evaluators", "Evaluators"),
    "OpParams": ("params", "OpParams"),
    "OpWorkflowRunner": ("runner", "OpWorkflowRunner"),
    "OpApp": ("runner", "OpApp"),
    "RunType": ("runner", "RunType"),
    "ModelInsights": ("insights", "ModelInsights"),
    "RecordInsightsLOCO": ("record_insights", "RecordInsightsLOCO"),
    "RecordInsightsCorr": ("record_insights", "RecordInsightsCorr"),
    "RawFeatureFilter": ("filters", "RawFeatureFilter"),
    "score_function": ("local", "score_function"),
    "transmogrify": ("ops.transmogrify", "transmogrify"),
    "RetryPolicy": ("resilience", "RetryPolicy"),
    "FailureLog": ("resilience", "FailureLog"),
    "FaultInjector": ("resilience", "FaultInjector"),
    "InjectedFault": ("resilience", "InjectedFault"),
    "WatchdogTimeout": ("resilience", "WatchdogTimeout"),
    "AllCandidatesFailed": ("resilience", "AllCandidatesFailed"),
    "run_with_deadline": ("resilience", "run_with_deadline"),
    "use_failure_log": ("resilience", "use_failure_log"),
    "inject_faults": ("resilience", "inject_faults"),
    "CheckpointError": ("checkpoint", "CheckpointError"),
    "CorruptModelError": ("checkpoint", "CorruptModelError"),
    "ModelVersionError": ("checkpoint", "ModelVersionError"),
    "TrainingPreempted": ("checkpoint", "TrainingPreempted"),
    "SweepCheckpoint": ("checkpoint", "SweepCheckpoint"),
    "verify_bundle": ("checkpoint", "verify_bundle"),
    "atomic_bundle_write": ("checkpoint", "atomic_bundle_write"),
    "preemption_guard": ("checkpoint", "preemption_guard"),
    "shutdown_requested": ("checkpoint", "shutdown_requested"),
    "Tracer": ("telemetry", "Tracer"),
    "use_tracer": ("telemetry", "use_tracer"),
    "active_tracer": ("telemetry", "active_tracer"),
    "span": ("telemetry", "span"),
    "current_span_id": ("telemetry", "current_span_id"),
    "MetricsRegistry": ("telemetry", "MetricsRegistry"),
    "telemetry_summary": ("telemetry", "telemetry_summary"),
}


def __getattr__(name):
    # Lazy imports of heavier submodules to keep `import transmogrifai_tpu` fast.
    if name in _LAZY:
        import importlib
        mod_name, attr = _LAZY[name]
        mod = importlib.import_module(f".{mod_name}", __name__)
        return getattr(mod, attr)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
