"""Masked on-device metrics for the CV loop.

Why this exists: on real TPU hardware the host link can be orders of magnitude
slower than HBM (observed ~13 MB/s h2d / ~4 MB/s d2h through the axon tunnel),
so pulling per-candidate prediction vectors to the host to score them — the
obvious port of the reference's evaluator.evaluateAll(Dataset) — costs more
than all the training matmuls combined.  Instead every validation metric is a
jitted reduction over the FULL row set with a 0/1 validation mask, so fold
slicing never changes array shapes (one compile covers every fold) and only
the final scalar crosses the link.

Ties are handled exactly (midranks for AuROC, threshold grouping for AuPR)
via the sorted-searchsorted trick: for sorted scores, searchsorted(s, s,
"left"/"right") gives each row's tie-group boundaries without dynamic shapes.

≙ reference evaluators OpBinaryClassificationEvaluator.scala:67-185 /
OpRegressionEvaluator / OpMultiClassificationEvaluator semantics.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


@jax.jit
def masked_auroc(y: jnp.ndarray, scores: jnp.ndarray, w: jnp.ndarray):
    """Weighted Mann-Whitney AUC with exact tie handling.  ``w`` is a 0/1 (or
    weighted) row mask; rows with w=0 are ignored."""
    order = jnp.argsort(scores)
    ss = scores[order]
    yy = y[order]
    ww = w[order]
    wpos = ww * (yy > 0.5)
    wneg = ww * (yy <= 0.5)
    prefix_neg = jnp.concatenate([jnp.zeros(1, wneg.dtype), jnp.cumsum(wneg)])
    left = jnp.searchsorted(ss, ss, side="left")
    right = jnp.searchsorted(ss, ss, side="right")
    below = prefix_neg[left]                   # neg weight strictly below
    same = prefix_neg[right] - prefix_neg[left]  # neg weight in tie group
    num = jnp.sum(wpos * (below + 0.5 * same))
    n_pos = jnp.sum(wpos)
    n_neg = jnp.sum(wneg)
    return jnp.where(n_pos * n_neg > 0, num / jnp.maximum(n_pos * n_neg, 1e-12), 0.0)


@jax.jit
def masked_aupr(y: jnp.ndarray, scores: jnp.ndarray, w: jnp.ndarray):
    """Weighted area under the PR curve, MLlib-style (threshold-grouped,
    trapezoid over recall with a prepended (0, 1) point)."""
    order = jnp.argsort(-scores)
    ss = scores[order]
    yy = y[order]
    ww = w[order]
    tp_run = jnp.cumsum(ww * (yy > 0.5))
    fp_run = jnp.cumsum(ww * (yy <= 0.5))
    # group rows by distinct threshold: every row reads its tie-group's LAST
    # cumsum (the value at the threshold boundary); duplicated points then
    # contribute zero width to the trapezoid
    neg = -ss  # ascending for searchsorted
    right = jnp.searchsorted(neg, neg, side="right") - 1
    tp = tp_run[right]
    fp = fp_run[right]
    n_pos = jnp.maximum(tp_run[-1], 1e-12)
    precision = tp / jnp.maximum(tp + fp, 1e-12)
    recall = tp / n_pos
    recall = jnp.concatenate([jnp.zeros(1, recall.dtype), recall])
    precision = jnp.concatenate([jnp.ones(1, precision.dtype), precision])
    return jnp.where(tp_run[-1] > 0,
                     jnp.trapezoid(precision, recall), 0.0)


@jax.jit
def masked_auroc_grid(y: jnp.ndarray, S: jnp.ndarray, W: jnp.ndarray):
    """``masked_auroc`` for K candidate score columns at once: S [N, K] →
    [K] AUCs in ONE program (the CV grid's per-candidate metric dispatches
    collapse to a single one).  ``W`` is either one shared [N] mask (a
    fold's validation rows — no K-fold duplication of mask HBM) or
    per-candidate [K, N] masks."""
    if W.ndim == 1:
        return jax.vmap(lambda s: masked_auroc(y, s, W), in_axes=1)(S)
    return jax.vmap(lambda s, w: masked_auroc(y, s, w), in_axes=(1, 0))(S, W)


@jax.jit
def masked_aupr_grid(y: jnp.ndarray, S: jnp.ndarray, W: jnp.ndarray):
    """``masked_aupr`` over K score columns (see masked_auroc_grid)."""
    if W.ndim == 1:
        return jax.vmap(lambda s: masked_aupr(y, s, W), in_axes=1)(S)
    return jax.vmap(lambda s, w: masked_aupr(y, s, w), in_axes=(1, 0))(S, W)


@jax.jit
def masked_auroc_fold_grid(y: jnp.ndarray, S: jnp.ndarray, W: jnp.ndarray):
    """The whole (fold × grid) AUC panel in ONE program: S [N, F, G] score
    columns, W [F, N] per-fold validation masks → [F, G].  Replaces one
    grid-metric dispatch (plus an eager S slice) per fold, without
    duplicating mask HBM across grid points — the masks stay [F, N]."""
    return jax.vmap(
        lambda s, w: jax.vmap(lambda c: masked_auroc(y, c, w), in_axes=1)(s),
        in_axes=(1, 0))(S, W)


@jax.jit
def masked_aupr_fold_grid(y: jnp.ndarray, S: jnp.ndarray, W: jnp.ndarray):
    """``masked_aupr`` over the (fold × grid) panel (see
    masked_auroc_fold_grid)."""
    return jax.vmap(
        lambda s, w: jax.vmap(lambda c: masked_aupr(y, c, w), in_axes=1)(s),
        in_axes=(1, 0))(S, W)


@jax.jit
def masked_binary_confusion(y: jnp.ndarray, yhat: jnp.ndarray, w: jnp.ndarray):
    """Returns [tp, fp, tn, fn] weighted counts as ONE stacked array (a single
    scalar-block transfer over the host link)."""
    yp = y > 0.5
    hp = yhat > 0.5
    return jnp.stack([jnp.sum(w * (yp & hp)), jnp.sum(w * (~yp & hp)),
                      jnp.sum(w * (~yp & ~hp)), jnp.sum(w * (yp & ~hp))])


@jax.jit
def masked_reg_errors(y: jnp.ndarray, yhat: jnp.ndarray, w: jnp.ndarray):
    """Returns [mse, mae] over masked rows as one stacked array."""
    wsum = jnp.maximum(jnp.sum(w), 1e-12)
    err = yhat - y
    return jnp.stack([jnp.sum(w * err * err) / wsum,
                      jnp.sum(w * jnp.abs(err)) / wsum])


@functools.partial(jax.jit, static_argnames=("n_classes",))
def masked_multiclass_confusion(y: jnp.ndarray, yhat: jnp.ndarray,
                                w: jnp.ndarray, *, n_classes: int):
    """Weighted [C, C] confusion matrix via one-hot matmul on the MXU."""
    yo = jax.nn.one_hot(y.astype(jnp.int32), n_classes, dtype=jnp.float32)
    ho = jax.nn.one_hot(yhat.astype(jnp.int32), n_classes, dtype=jnp.float32)
    return (yo * w[:, None]).T @ ho


def _masked_reg_metric(y, yhat, w, metric):
    errs = masked_reg_errors(y, yhat, w)
    if metric == "RootMeanSquaredError":
        return jnp.sqrt(errs[0])
    if metric == "MeanSquaredError":
        return errs[0]
    return errs[1]                                  # MeanAbsoluteError


@functools.partial(jax.jit, static_argnames=("metric",))
def masked_reg_metric_grid(y: jnp.ndarray, S: jnp.ndarray, W: jnp.ndarray,
                           *, metric: str):
    """Regression analog of ``masked_auroc_grid``: S [N, K] holds K
    candidates' PREDICTION columns (linear-regression margins ARE the
    predictions, so the panel is exact, not merely rank-equivalent) →
    [K] device scalars of the chosen error metric."""
    if W.ndim == 1:
        return jax.vmap(lambda s: _masked_reg_metric(y, s, W, metric),
                        in_axes=1)(S)
    return jax.vmap(lambda s, w: _masked_reg_metric(y, s, w, metric),
                    in_axes=(1, 0))(S, W)


@functools.partial(jax.jit, static_argnames=("metric",))
def masked_reg_metric_fold_grid(y: jnp.ndarray, S: jnp.ndarray,
                                W: jnp.ndarray, *, metric: str):
    """Whole (fold × grid) regression panel: S [N, F, G] predictions,
    W [F, N] fold masks → [F, G]."""
    return jax.vmap(
        lambda s, w: jax.vmap(
            lambda c: _masked_reg_metric(y, c, w, metric), in_axes=1)(s),
        in_axes=(1, 0))(S, W)


def _conf_metric(conf, metric):
    """Weighted Precision/Recall/F1/Error from a [C, C] device confusion
    matrix — the jnp twin of OpMultiClassificationEvaluator._conf_panel
    (identical zero-guard semantics, so the fused panel matches the host
    per-candidate path bit-for-bit up to f32 rounding)."""
    support = conf.sum(axis=1)
    tp = jnp.diagonal(conf)
    if metric == "Error":
        return 1.0 - tp.sum() / jnp.maximum(support.sum(), 1.0)
    pred_count = conf.sum(axis=0)
    prec_c = jnp.where(pred_count > 0, tp / jnp.maximum(pred_count, 1e-30),
                       0.0)
    rec_c = jnp.where(support > 0, tp / jnp.maximum(support, 1e-30), 0.0)
    wts = support / jnp.maximum(support.sum(), 1.0)
    if metric == "Precision":
        return wts @ prec_c
    if metric == "Recall":
        return wts @ rec_c
    f1_c = jnp.where(prec_c + rec_c > 0,
                     2.0 * prec_c * rec_c / jnp.maximum(prec_c + rec_c,
                                                        1e-30), 0.0)
    return wts @ f1_c


@functools.partial(jax.jit, static_argnames=("n_classes", "metric"))
def masked_multiclass_metric_grid(y: jnp.ndarray, P: jnp.ndarray,
                                  W: jnp.ndarray, *, n_classes: int,
                                  metric: str):
    """Multiclass analog of ``masked_auroc_grid``: P [N, K] holds K
    candidates' integer PREDICTION columns → [K] device scalars of the
    weighted confusion metric.  Classes absent from the data contribute
    zero support/zero weight, so a generous static ``n_classes`` is exact."""
    def one(p, w):
        conf = masked_multiclass_confusion(y, p, w, n_classes=n_classes)
        return _conf_metric(conf, metric)
    if W.ndim == 1:
        return jax.vmap(lambda p: one(p, W), in_axes=1)(P)
    return jax.vmap(one, in_axes=(1, 0))(P, W)


@functools.partial(jax.jit, static_argnames=("n_classes", "metric"))
def masked_multiclass_metric_fold_grid(y: jnp.ndarray, P: jnp.ndarray,
                                       W: jnp.ndarray, *, n_classes: int,
                                       metric: str):
    """Whole (fold × grid) multiclass panel: P [N, F, G] integer
    predictions, W [F, N] fold masks → [F, G]."""
    def one(p, w):
        conf = masked_multiclass_confusion(y, p, w, n_classes=n_classes)
        return _conf_metric(conf, metric)
    return jax.vmap(
        lambda p, w: jax.vmap(lambda c: one(c, w), in_axes=1)(p),
        in_axes=(1, 0))(P, W)


@jax.jit
def masked_threshold_confusion(y: jnp.ndarray, scores: jnp.ndarray,
                               w: jnp.ndarray, thresholds: jnp.ndarray):
    """Per-threshold [4, T] weighted (tp, fp, tn, fn) in one fused program:
    scores are bucketed into the threshold grid with searchsorted, then the
    per-threshold counts are suffix sums of a [T+1]-bin histogram — no [T, N]
    broadcast ever materializes (≙ the reference evaluator's
    thresholds panel, OpBinaryClassificationEvaluator.scala:67-185)."""
    wpos = w * (y > 0.5)
    wneg = w * (y <= 0.5)
    # bin i ⇔ thresholds[i-1] <= s < thresholds[i]; prediction at threshold t
    # is s >= t, so counts at t = sum of bins >= its index (suffix sum)
    bins = jnp.searchsorted(thresholds, scores, side="right")
    T = thresholds.shape[0]
    pos_hist = jax.ops.segment_sum(wpos, bins, num_segments=T + 1)
    neg_hist = jax.ops.segment_sum(wneg, bins, num_segments=T + 1)
    pos_suffix = jnp.cumsum(pos_hist[::-1])[::-1]
    neg_suffix = jnp.cumsum(neg_hist[::-1])[::-1]
    tp = pos_suffix[1:]
    fp = neg_suffix[1:]
    n_pos = jnp.sum(wpos)
    n_neg = jnp.sum(wneg)
    return jnp.stack([tp, fp, n_neg - fp, n_pos - tp])
