"""Purity / NaN discipline checks — the TPU analog of the reference's
closure-serializability validation (utils ClosureUtils.checkSerializable,
enforced at OpWorkflow.scala:277-335) and of JVM-side sanitizers
(SURVEY.md §5 "Race detection / sanitizers": the JAX equivalents are
``jax.debug_nans`` and pure-function discipline in traced stages).

Three checks, all opt-in via ``Workflow.with_sanitizers()``:

  * **NaN guard** — enables ``jax_debug_nans`` for the duration of ``train()``
    so the first NaN-producing primitive raises at its origin instead of
    corrupting downstream fits silently.
  * **Purity audit** — every fitted transformer is applied twice to the same
    batch; outputs must match bitwise.  Catches side-effecting or
    RNG-without-seed ``transform`` implementations, which would break the
    compiled score program (same trace, different results) exactly the way a
    non-serializable closure broke Spark jobs.
  * **Serialization audit** — every stage must JSON-round-trip
    (≙ the reference's uid/ctor-args validation, OpWorkflow.scala:292-317).
"""

from __future__ import annotations

import contextlib
from typing import Any, Dict, List, Optional

import numpy as np

from .columns import ColumnBatch


class PurityError(RuntimeError):
    """A stage's transform is not a pure function of its inputs."""


@contextlib.contextmanager
def nan_guard(enable: bool = True):
    """Context manager toggling ``jax_debug_nans`` (restores prior value)."""
    import jax

    if not enable:
        yield
        return
    prev = jax.config.jax_debug_nans
    jax.config.update("jax_debug_nans", True)
    try:
        yield
    finally:
        jax.config.update("jax_debug_nans", prev)


def _col_payload(col) -> List[np.ndarray]:
    vals = col.values
    if isinstance(vals, dict):
        return [np.asarray(v) for v in vals.values() if v is not None]
    return [np.asarray(vals)]


def _equal(a: List[np.ndarray], b: List[np.ndarray]) -> bool:
    if len(a) != len(b):
        return False
    for x, y in zip(a, b):
        if x.shape != y.shape or x.dtype != y.dtype:
            return False
        if x.dtype == object:
            def same(u, v):
                if u is v:
                    return True
                # NaN != NaN would flag bitwise-identical outputs as impure
                if isinstance(u, float) and isinstance(v, float):
                    return u == v or (u != u and v != v)
                return u == v
            if not all(same(u, v) for u, v in zip(x.ravel(), y.ravel())):
                return False
        elif not np.array_equal(x, y, equal_nan=True):
            return False
    return True


def audit_stage_purity(stage, batch: ColumnBatch) -> None:
    """Apply ``stage.transform_batch`` twice; raise PurityError on any
    difference (side effects, unseeded RNG, input mutation)."""
    out1 = stage.transform_batch(batch)
    out2 = stage.transform_batch(batch)
    for f in stage.output_features:
        if not _equal(_col_payload(out1[f.name]), _col_payload(out2[f.name])):
            raise PurityError(
                f"stage {stage.operation_name} ({stage.uid}) is impure: "
                f"output {f.name!r} differs across identical applications — "
                "traced stages must be pure functions of their inputs")


def audit_dag_purity(fitted_dag, batch: ColumnBatch) -> None:
    """Sweep every fitted transformer in DAG order (each stage audited on the
    batch state it actually sees)."""
    from .stages.base import Transformer

    b = batch
    for layer in fitted_dag:
        for st in layer:
            if isinstance(st, Transformer):
                audit_stage_purity(st, b)
        for st in layer:
            if isinstance(st, Transformer):
                b = st.transform_batch(b)


def audit_stage_serialization(stages) -> None:
    """Every stage must produce JSON-serializable ctor args
    (≙ OpWorkflow.validateStages serializability check)."""
    import json

    from .stages.serialization import stage_to_json

    for st in stages:
        try:
            d = stage_to_json(st)
            json.dumps(d)
        except Exception as e:  # noqa: BLE001
            raise PurityError(
                f"stage {st.operation_name} ({st.uid}) does not serialize: "
                f"{e} — stage params must be JSON-encodable "
                "(≙ ClosureUtils.checkSerializable)") from e
        # stage_to_json nulls what it cannot encode; a param silently lost is
        # exactly the state a reloaded model would be missing
        saved = d.get("params", {})
        for k, v in st.params.items():
            if v is not None and saved.get(k) is None:
                raise PurityError(
                    f"stage {st.operation_name} ({st.uid}) does not "
                    f"serialize: param {k!r} (= {type(v).__name__}) is not "
                    "JSON-encodable and would be lost on save/load "
                    "(≙ ClosureUtils.checkSerializable)")
