"""Memory governance for the training/streaming paths (ISSUE 15).

Every 11M-row attempt in ``BENCH_11M_ATTEMPTS_r4.json`` died the same way:
a TPU worker hard-faulted inside ``batched_device_put`` and a human
re-launched with a smaller hand-picked budget ("budget4/cache256M" →
"budget2/cache128M").  This module makes the runtime walk that ladder
itself, in four pieces:

* **Budget discovery** — per-device capacity from
  ``TRANSMOGRIFAI_DEVICE_MEM_BYTES`` (operator override / ``memoryParams``
  mirror) or ``device.memory_stats()`` where the backend reports it
  (guarded: CPU backends usually return nothing).
* **Preflight planning** — before any ``stream_to_device``/``device_put``,
  :func:`plan_sweep_memory` estimates the padded-ladder-rung × dtype ×
  grid-width × fused-fold-panel footprint (plus an XLA temp headroom
  factor) against the budget and picks the streaming chunk bytes and a
  candidate-grid partitioning up front — OOM becomes a plan, not a crash.
* **Typed classification** — :func:`is_memory_exhaustion` is the sibling of
  ``supervisor.is_device_loss``: a conservative string/errtype matrix
  (RESOURCE_EXHAUSTED, "out of memory", allocator messages) that NEVER
  overlaps device loss, producing :class:`MemoryExhaustedError` with the
  attempted plan attached.  The two classifiers route to different
  recoveries: device loss shrinks the mesh; memory exhaustion shrinks the
  *work* via the degrade ladder below.
* **Shrink-and-retry ladder + host watchdog** — on classified OOM the sweep
  walks a deterministic degrade ladder (halve streaming chunk bytes →
  partition the candidate grid into sub-batches → collapse the model axis →
  per-candidate fallback), each step a ``degraded`` FailureLog note and a
  ``memory.shrink`` telemetry event, resuming from the ``SweepCheckpoint``.
  :class:`RssWatchdog` is the host-side analog: soft watermark sheds
  pretrace queues and device-transfer caches, hard watermark raises typed
  :class:`HostMemoryPressure` instead of letting the kernel OOM-killer
  choose a victim.

Everything here reads the environment per call (the ``memoryParams`` →
``TRANSMOGRIFAI_*`` mirror in ``runner.py`` composes with operator
overrides), and every collaborator of the watchdog (clock, RSS reader,
shedders) is injectable so the state machine tests run on a fake clock.
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence

from ..resilience import InjectedFault, maybe_inject, record_failure

# headroom multiplying the analytic footprint estimate: XLA temporaries,
# fusion scratch, and the double-buffered staging copies are real bytes the
# formula cannot see
_DEFAULT_HEADROOM = 1.5
# ladder steps, in the order the shrink-and-retry walks them
LADDER_STEPS = ("halve_chunk_bytes", "partition_grid",
                "collapse_model_axis", "per_candidate_fallback")


class MemoryExhaustedError(RuntimeError):
    """Typed device-memory exhaustion, carrying the plan that was being
    attempted when the allocator gave up — the post-mortem starts with
    ``e.plan`` instead of a grep through allocator spew."""

    def __init__(self, message: str, plan: Optional["MemoryPlan"] = None):
        super().__init__(message)
        self.plan = plan


class HostMemoryPressure(RuntimeError):
    """Host RSS crossed the hard watermark: typed, raised by governed code
    (via :func:`check_host_pressure`) before the kernel OOM-killer picks a
    victim for us."""


# --------------------------------------------------------------------------
# enablement + budget discovery
# --------------------------------------------------------------------------

def memory_governor_enabled() -> bool:
    """Preflight planning + shrink-and-retry are ON by default
    (TRANSMOGRIFAI_MEMORY_GOVERNOR=0 / ``--no-memory-governor`` opt out)."""
    return os.environ.get("TRANSMOGRIFAI_MEMORY_GOVERNOR", "1") != "0"


def memory_headroom() -> float:
    """XLA-temp headroom factor applied to the analytic footprint estimate
    (TRANSMOGRIFAI_MEMORY_HEADROOM, default 1.5)."""
    try:
        v = float(os.environ.get("TRANSMOGRIFAI_MEMORY_HEADROOM",
                                 str(_DEFAULT_HEADROOM)))
    except ValueError:
        return _DEFAULT_HEADROOM
    return v if v >= 1.0 else _DEFAULT_HEADROOM


def device_memory_budget() -> Optional[int]:
    """Per-device memory budget in bytes: the operator override
    (TRANSMOGRIFAI_DEVICE_MEM_BYTES, mirrored from
    ``memoryParams.deviceMemBytes``) wins; otherwise the backend's own
    ``memory_stats()`` limit where reported (TPU/GPU runtimes do, CPU
    usually doesn't); ``None`` = unknown, the planner passes through."""
    v = os.environ.get("TRANSMOGRIFAI_DEVICE_MEM_BYTES")
    if v:
        try:
            n = int(float(v))
            return n if n > 0 else None
        except ValueError:
            pass
    try:
        import jax
        stats = jax.local_devices()[0].memory_stats()
        if stats:
            for key in ("bytes_limit", "bytes_reservable_limit"):
                lim = stats.get(key)
                if lim:
                    return int(lim)
    except Exception:  # noqa: BLE001 — unknown budget is a valid answer
        pass
    return None


def max_oom_recoveries() -> int:
    """How many degrade-ladder steps one sweep may take on classified OOM
    (TRANSMOGRIFAI_OOM_RECOVERIES, default = the full ladder); 0 when the
    governor is off — memory errors then propagate like any other."""
    if not memory_governor_enabled():
        return 0
    try:
        return max(0, int(os.environ.get("TRANSMOGRIFAI_OOM_RECOVERIES",
                                         str(len(LADDER_STEPS)))))
    except ValueError:
        return len(LADDER_STEPS)


# --------------------------------------------------------------------------
# typed classification (sibling of supervisor.is_device_loss)
# --------------------------------------------------------------------------

# allocator/runtime phrasings that mean "the device ran out of memory" —
# conservative on purpose: a bad hyper-parameter or a compile error must
# keep its per-candidate degrade path, and NOTHING here may overlap the
# device-loss matrix (UNAVAILABLE / DEVICE_LOST), which routes to the
# surviving-mesh recovery instead
_OOM_MARKERS = (
    "RESOURCE_EXHAUSTED",
    "resource exhausted",
    "out of memory",
    "oom when allocating",
    "failed to allocate",
    "allocation failure",
    "exceeds the memory available",
    "memory.device_oom",   # injected chaos marker (InjectedFault str)
)


def is_memory_exhaustion(e: BaseException) -> bool:
    """Classify an exception as device-memory exhaustion (vs an ordinary
    candidate failure OR a device loss).  The shrink-and-retry ladder only
    fires on these; everything else keeps its existing path."""
    if isinstance(e, MemoryExhaustedError):
        return True
    if isinstance(e, MemoryError):
        return True
    from .supervisor import is_device_loss
    if is_device_loss(e):
        return False   # disjoint by construction: mesh shrink, not ladder
    s = str(e).lower()
    return any(m.lower() in s for m in _OOM_MARKERS)


# --------------------------------------------------------------------------
# preflight planning
# --------------------------------------------------------------------------

@dataclass
class MemoryPlan:
    """What the sweep is about to ask of each device, and what the planner
    chose about it.  Attached to :class:`MemoryExhaustedError` and recorded
    in bench ``aux.memory`` so failed attempts document themselves."""

    rows: int                      # padded ladder-rung row count
    cols: int
    folds: int                     # fused fold panels
    grid_width: int                # widest candidate family grid
    devices: int
    dtype_bytes: int
    headroom: float
    device_budget: Optional[int]   # bytes per device; None = unknown
    est_device_bytes: int          # estimated per-device peak footprint
    chunk_bytes: int               # chosen streaming chunk budget
    grid_parts: int = 1            # candidate-grid sub-batches
    shrinks: List[str] = field(default_factory=list)  # ladder steps applied
    nnz: Optional[int] = None      # sparse payload: real COO entry count

    def fits(self) -> bool:
        return (self.device_budget is None
                or self.est_device_bytes <= self.device_budget)

    def to_json(self) -> Dict[str, Any]:
        return {"rows": self.rows, "cols": self.cols, "folds": self.folds,
                "gridWidth": self.grid_width, "devices": self.devices,
                "dtypeBytes": self.dtype_bytes, "headroom": self.headroom,
                "deviceBudgetBytes": self.device_budget,
                "estDeviceBytes": self.est_device_bytes,
                "chunkBytes": self.chunk_bytes,
                "gridParts": self.grid_parts,
                "fits": self.fits(), "shrinks": list(self.shrinks),
                "nnz": self.nnz}


_PLAN_LOCK = threading.Lock()
_LAST_PLAN: Optional[MemoryPlan] = None


def last_plan() -> Optional[MemoryPlan]:
    """The most recent preflight plan (bench aux, error attachment)."""
    with _PLAN_LOCK:
        return _LAST_PLAN


def estimate_sweep_device_bytes(*, rows: int, cols: int, folds: int,
                                grid_width: int, devices: int,
                                dtype_bytes: int = 4,
                                headroom: Optional[float] = None,
                                nnz: Optional[int] = None) -> int:
    """Analytic per-device footprint of one fused sweep: the row-sharded
    matrix shard, the fold weight/validation panels ((2·folds+1) row
    vectors: train masks, validation masks, labels), and the per-lane
    working set of the batched (fold × grid) fit programs (coefficients +
    metric panels per lane), all under the XLA-temp headroom factor.

    ``nnz`` marks a sparse COO payload: the resident matrix is then the
    ladder-rounded entry capacity × 3 flat components (value/col/row, one
    dtype word each), not ``rows × cols`` — the dense-equivalent estimate
    over-counts hashed-text matrices by orders of magnitude and would
    shrink the plan for memory the sweep never allocates."""
    devices = max(1, int(devices))
    h = memory_headroom() if headroom is None else max(1.0, float(headroom))
    if nnz is not None:
        from ..sparse.matrix import nnz_capacity
        per = -(-int(nnz) // devices)
        matrix = devices * nnz_capacity(per) * 3
    else:
        matrix = rows * cols
    panels = (2 * folds + 1) * rows
    lanes = grid_width * folds * (cols + 8)
    return int((matrix + panels) * dtype_bytes * h / devices
               + lanes * dtype_bytes * h)


def plan_sweep_memory(*, rows: int, cols: int, folds: int, grid_width: int,
                      devices: int = 1, dtype_bytes: int = 4,
                      budget: Optional[int] = None,
                      chunk_bytes: Optional[int] = None,
                      nnz: Optional[int] = None) -> MemoryPlan:
    """Choose chunk bytes and grid partitioning BEFORE the first transfer.

    Deterministic: the same shapes and budget always produce the same plan.
    The chunk budget halves until two staging buffers (double buffering)
    fit comfortably beside the resident estimate; when the resident
    estimate itself exceeds the device budget the candidate grid splits
    into sub-batches (halving the per-lane working set per step) — the
    same degrade the runtime ladder applies reactively, applied up front.
    Applied ladder shrinks (:func:`grid_partitions` etc.) fold in so a
    post-OOM replan starts from the degraded state, not from scratch."""
    from .streaming import device_chunk_bytes
    if budget is None:
        budget = device_memory_budget()
    base_chunk = chunk_bytes if chunk_bytes is not None \
        else device_chunk_bytes()
    chunk = effective_chunk_bytes(base_chunk)
    parts = grid_partitions()
    shrinks = []
    est = estimate_sweep_device_bytes(
        rows=rows, cols=cols, folds=folds,
        grid_width=-(-grid_width // parts), devices=devices,
        dtype_bytes=dtype_bytes, nnz=nnz)
    if budget is not None:
        # two chunk-sized staging buffers live beside the resident set
        # during streaming; keep them under a quarter of the budget
        while chunk > (1 << 20) and 2 * chunk > budget // 4:
            chunk //= 2
            shrinks.append("halve_chunk_bytes")
        while est > budget and parts < max(1, grid_width):
            parts *= 2
            shrinks.append("partition_grid")
            est = estimate_sweep_device_bytes(
                rows=rows, cols=cols, folds=folds,
                grid_width=-(-grid_width // parts), devices=devices,
                dtype_bytes=dtype_bytes, nnz=nnz)
    plan = MemoryPlan(rows=int(rows), cols=int(cols), folds=int(folds),
                      grid_width=int(grid_width), devices=int(devices),
                      dtype_bytes=int(dtype_bytes),
                      headroom=memory_headroom(), device_budget=budget,
                      est_device_bytes=int(est), chunk_bytes=int(chunk),
                      grid_parts=int(parts), shrinks=shrinks,
                      nnz=None if nnz is None else int(nnz))
    global _LAST_PLAN
    with _PLAN_LOCK:
        _LAST_PLAN = plan
    try:
        from ..telemetry import REGISTRY, event
        REGISTRY.gauge("memory.plan_bytes").set(plan.est_device_bytes)
        REGISTRY.gauge("memory.chunk_bytes").set(plan.chunk_bytes)
        if budget is not None:
            REGISTRY.gauge("memory.budget_bytes").set(budget)
        if shrinks or not plan.fits():
            event("memory.plan", **plan.to_json())
    except Exception:  # noqa: BLE001 — planning must not fail the sweep
        pass
    return plan


def estimate_batch_bytes(rows: int, features: int,
                         dtype_bytes: int = 4) -> int:
    """Serving-side footprint estimate of one scoring batch (the admission
    controller's memory signal): rows × feature width × dtype under the
    same headroom factor the training planner uses."""
    return int(rows * max(1, int(features)) * dtype_bytes
               * memory_headroom())


# --------------------------------------------------------------------------
# the degrade ladder (process-ambient, like the surviving-device cap)
# --------------------------------------------------------------------------

_LADDER_LOCK = threading.Lock()
_SHRINK_LEVEL = 0


def shrink_level() -> int:
    """Ladder rungs applied so far this process (0 = unpressured)."""
    with _LADDER_LOCK:
        return _SHRINK_LEVEL


def reset_memory_degrade() -> None:
    """Clear the ladder (tests; operator action after pressure clears)."""
    global _SHRINK_LEVEL
    with _LADDER_LOCK:
        _SHRINK_LEVEL = 0


def _level() -> int:
    with _LADDER_LOCK:
        return _SHRINK_LEVEL


def effective_chunk_bytes(base: int) -> int:
    """Streaming chunk budget under the ladder: every rung ≥1 halves it
    once more (rung 1 halves, rung 2 quarters, ...), floor 1MB — the
    deepest rungs keep shrinking staging while they also shrink work."""
    lvl = _level()
    if lvl <= 0:
        return int(base)
    return max(1 << 20, int(base) >> lvl)


def grid_partitions() -> int:
    """Candidate-grid sub-batches (rung ≥2 doubles per rung: one batched
    (fold × grid) program becomes 2, 4, ... smaller ones)."""
    lvl = _level()
    return 1 if lvl < 2 else 1 << (lvl - 1)


def model_axis_collapsed() -> bool:
    """Rung ≥3: give the model axis's devices back to the data axis so
    each candidate lane spans more HBM."""
    return _level() >= 3


def per_candidate_fallback() -> bool:
    """Rung ≥4 (last resort): skip the batched grid programs entirely and
    refit per (fold, grid point) — smallest possible working set."""
    return _level() >= 4


def note_sweep_memory_exhaustion(e: BaseException, *, attempt: int = 0,
                                 stage: str = "validator") -> int:
    """One observable bundle per mid-sweep OOM: failure-log ``degraded``
    at point ``memory.device_oom``, the ``memory.shrinks_total`` counter,
    a ``memory.shrink`` telemetry event naming the ladder step taken, and
    the new shrink level (returned)."""
    global _SHRINK_LEVEL
    with _LADDER_LOCK:
        _SHRINK_LEVEL += 1
        lvl = _SHRINK_LEVEL
    step = LADDER_STEPS[min(lvl, len(LADDER_STEPS)) - 1]
    record_failure(stage, "degraded", e, point="memory.device_oom",
                   attempt=attempt, fallback=f"memory ladder: {step}")
    try:
        from ..telemetry import REGISTRY, event
        REGISTRY.counter("memory.shrinks_total").inc()
        REGISTRY.gauge("memory.shrink_level").set(lvl)
        event("memory.shrink", attempt=attempt, level=lvl, step=step,
              cause=f"{type(e).__name__}: {e}"[:200])
        from ..obsv import blackbox_note
        blackbox_note("memory.shrink", attempt=attempt, level=lvl,
                      step=step, cause=f"{type(e).__name__}: {e}"[:200])
    except Exception:  # noqa: BLE001
        pass
    return lvl


def as_memory_exhausted(e: BaseException) -> MemoryExhaustedError:
    """Wrap a classified allocator error into the typed form with the
    attempted plan attached (idempotent for already-typed errors)."""
    if isinstance(e, MemoryExhaustedError):
        if e.plan is None:
            e.plan = last_plan()
        return e
    return MemoryExhaustedError(
        f"device memory exhausted: {type(e).__name__}: {e}",
        plan=last_plan())


# --------------------------------------------------------------------------
# host-side RSS watchdog
# --------------------------------------------------------------------------

def _read_rss_bytes() -> int:
    """Current RSS from /proc/self/statm (pages × page size); 0 when the
    proc filesystem is unavailable (macOS tests inject a reader)."""
    try:
        with open("/proc/self/statm") as fh:
            pages = int(fh.read().split()[1])
        return pages * os.sysconf("SC_PAGE_SIZE")
    except Exception:  # noqa: BLE001
        return 0


def _default_shedders() -> Sequence[Callable[[], int]]:
    """What soft pressure is allowed to drop: queued (not-yet-started)
    background pre-traces, and the host→device transfer cache.  Both are
    pure performance state — correctness never depends on either."""
    def shed_pretrace() -> int:
        from ..aot import pretrace_shed
        return pretrace_shed()

    def shed_device_cache() -> int:
        from ..columns import shed_device_cache
        return shed_device_cache()

    return (shed_pretrace, shed_device_cache)


def _env_bytes(name: str) -> Optional[int]:
    v = os.environ.get(name)
    if not v:
        return None
    try:
        n = int(float(v))
        return n if n > 0 else None
    except ValueError:
        return None


class RssWatchdog:
    """Heartbeat-style host-memory supervision with two watermarks.

    * below soft → state ``ok``;
    * RSS ≥ ``soft_bytes`` → state ``soft``: run the shedders (pretrace
      queue, device-transfer cache), record a ``shed`` FailureLog note and
      bump ``memory.host_soft_total`` — once per excursion, not per tick;
    * RSS ≥ ``hard_bytes`` → state ``hard``: record ``degraded``, bump
      ``memory.host_hard_total``, and trip the pressure flag —
      :func:`check_host_pressure` (called at sweep boundaries) then raises
      typed :class:`HostMemoryPressure` on the *governed* thread, where it
      can be handled, instead of letting the kernel OOM-killer act;
    * falling back below soft records ``recovered`` and clears the trip.

    Every collaborator (clock, RSS reader, shedders) is injectable and
    ``tick()`` is the synchronous unit the daemon loop repeats, mirroring
    ``supervisor.Heartbeat`` so the transition tests run on a fake clock
    with zero threads.  Gauges: ``memory.host_rss_bytes``,
    ``memory.watchdog_state`` (0 ok / 1 soft / 2 hard)."""

    _STATE_CODES = {"ok": 0, "soft": 1, "hard": 2}

    def __init__(self, *, soft_bytes: Optional[int] = None,
                 hard_bytes: Optional[int] = None,
                 interval_s: float = 10.0,
                 rss_reader: Callable[[], int] = _read_rss_bytes,
                 clock: Callable[[], float] = time.monotonic,
                 shedders: Optional[Sequence[Callable[[], int]]] = None):
        from ..telemetry import REGISTRY
        self._registry = REGISTRY
        self.soft_bytes = (soft_bytes if soft_bytes is not None
                           else _env_bytes("TRANSMOGRIFAI_HOST_MEM_SOFT_BYTES"))
        self.hard_bytes = (hard_bytes if hard_bytes is not None
                           else _env_bytes("TRANSMOGRIFAI_HOST_MEM_HARD_BYTES"))
        self.interval_s = float(interval_s)
        self._rss = rss_reader
        self._clock = clock
        self._shedders = (shedders if shedders is not None
                          else _default_shedders())
        self.state = "ok"
        self.tripped = False
        self.last_rss = 0
        self._ticks = 0
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._registry.gauge("memory.watchdog_state",
                             lambda: self._STATE_CODES[self.state])

    # -- one synchronous supervision step ----------------------------------
    def tick(self) -> str:
        with self._lock:
            tick_no = self._ticks
            self._ticks += 1
        rss = 0
        try:
            maybe_inject("memory.host_pressure", key=tick_no)
            rss = int(self._rss())
        except InjectedFault:
            # injected chaos: behave exactly as a hard-watermark reading
            rss = (self.hard_bytes if self.hard_bytes is not None
                   else (self.soft_bytes or 0) + 1)
        self.last_rss = rss
        self._registry.gauge("memory.host_rss_bytes").set(rss)
        if self.hard_bytes is not None and rss >= self.hard_bytes:
            new = "hard"
        elif self.soft_bytes is not None and rss >= self.soft_bytes:
            new = "soft"
        else:
            new = "ok"
        if new != self.state:
            self._transition(new, rss)
        return self.state

    def _transition(self, new: str, rss: int) -> None:
        old, self.state = self.state, new
        try:
            from ..telemetry import event
            event("memory.watchdog", from_state=old, to_state=new,
                  rss_bytes=rss)
        except Exception:  # noqa: BLE001
            pass
        if new == "hard":
            self.tripped = True
            record_failure("memory", "degraded",
                           f"host RSS {rss} >= hard watermark "
                           f"{self.hard_bytes}",
                           point="memory.host_pressure", rss_bytes=rss)
            self._registry.counter("memory.host_hard_total").inc()
        elif new == "soft":
            shed = self._run_shedders()
            record_failure("memory", "shed",
                           f"host RSS {rss} >= soft watermark "
                           f"{self.soft_bytes}; shed {shed} bytes of "
                           "caches/queues",
                           point="memory.host_pressure", rss_bytes=rss,
                           shed_bytes=shed)
            self._registry.counter("memory.host_soft_total").inc()
        else:
            self.tripped = False
            record_failure("memory", "recovered",
                           f"host RSS {rss} back below the soft watermark",
                           point="memory.host_pressure", rss_bytes=rss)

    def _run_shedders(self) -> int:
        total = 0
        for shed in self._shedders:
            try:
                total += int(shed() or 0)
            except Exception:  # noqa: BLE001 — shedding is best-effort
                pass
        return total

    def check(self) -> None:
        """Raise typed :class:`HostMemoryPressure` if the hard watermark
        tripped and has not recovered — the governed-thread half of the
        watchdog (sweep boundaries call this via
        :func:`check_host_pressure`)."""
        if self.tripped:
            raise HostMemoryPressure(
                f"host RSS {self.last_rss} crossed the hard watermark "
                f"{self.hard_bytes} bytes")

    # -- background loop ---------------------------------------------------
    def start(self) -> "RssWatchdog":
        with self._lock:
            if self._thread is not None:
                return self
            self._stop.clear()
            self._thread = threading.Thread(target=self._loop, daemon=True,
                                            name="memory-rss-watchdog")
            self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — supervision must not die
                pass
            self._stop.wait(self.interval_s)

    def stop(self, timeout_s: float = 5.0) -> None:
        self._stop.set()
        with self._lock:
            t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=timeout_s)


_WATCHDOG_LOCK = threading.Lock()
_WATCHDOG: Optional[RssWatchdog] = None


def install_watchdog(wd: Optional[RssWatchdog]) -> None:
    """Make ``wd`` the process-ambient watchdog (runner start/stop)."""
    global _WATCHDOG
    with _WATCHDOG_LOCK:
        _WATCHDOG = wd


def check_host_pressure() -> None:
    """Sweep-boundary hook: raises :class:`HostMemoryPressure` when the
    ambient watchdog's hard watermark has tripped; no-op otherwise."""
    with _WATCHDOG_LOCK:
        wd = _WATCHDOG
    if wd is not None:
        wd.check()


def watchdog_interval_s() -> float:
    """Background watchdog cadence (TRANSMOGRIFAI_RSS_WATCHDOG_S, default
    0 = no background thread; the watermarks still work synchronously for
    an explicitly-constructed watchdog)."""
    try:
        return float(os.environ.get("TRANSMOGRIFAI_RSS_WATCHDOG_S", "0"))
    except ValueError:
        return 0.0


def memory_aux() -> Dict[str, Any]:
    """Bench/artifact block: the plan that ran, the budget it ran under,
    and what the ladder did — so every BENCH attempt documents itself."""
    plan = last_plan()
    out: Dict[str, Any] = {
        "governor_enabled": memory_governor_enabled(),
        "device_budget_bytes": device_memory_budget(),
        "plan": plan.to_json() if plan is not None else None,
        "shrink_level": shrink_level(),
    }
    try:
        from ..telemetry import REGISTRY
        snap = REGISTRY.snapshot()
        out["shrinks_total"] = snap["counters"].get(
            "memory.shrinks_total", 0)
        # prefer the watchdog's last observation; fall back to a direct
        # read so artifacts document RSS even when no watchdog is running
        out["host_rss_bytes"] = (snap["gauges"].get("memory.host_rss_bytes")
                                 or _read_rss_bytes() or None)
    except Exception:  # noqa: BLE001
        pass
    return out
