"""Device-runtime supervisor — hang-proof probes, heartbeat, outage records.

The OUTAGE_r5 incident defined the failure mode this module exists for:
``jax.devices()`` / distributed init can HANG in native code with no error
raised, and plain SIGTERM does not kill the hung process — only SIGKILL
does.  ``resilience.run_with_deadline``'s thread watchdog can *raise* on the
hang but cannot *reclaim* the thread, so anything that must actually free
the resources has to live in a child process the parent can escalate-kill.
This module is that discipline as a subsystem instead of the three ad-hoc
copies the round-5 mitigations left in ``bench.py``, ``__graft_entry__.py``
and ``scripts/run_scale_bench.py``:

* ``run_supervised`` — run a child under a SIGTERM→SIGKILL escalation
  deadline (the ``timeout -k`` shape, as a library call).
* ``probe_devices`` / ``probe_with_backoff`` — a fresh child runs
  ``jax.devices()`` + a tiny compiled matmul and reports a structured
  :class:`ProbeVerdict` (available / degraded / outage, device inventory,
  probe latency).  This is the reference's RawFeatureFilter philosophy
  (validate before you commit compute) applied to hardware.
* ``Heartbeat`` — a background re-probe loop on a deterministic backoff
  schedule feeding a ``CircuitBreaker``, driving the
  AVAILABLE / DEGRADED / OUTAGE state machine exported through telemetry
  gauges and FailureLog actions (``outage`` / ``recovered``).
* ``write_outage_record`` — the standardized outage-record writer
  (the hand-written ``OUTAGE_r5.json`` shape, produced by code).
* surviving-device tracking + ``is_device_loss`` — on a mid-sweep device
  failure the validator shrinks the mesh policy to the surviving devices
  (``mark_device_loss``) and resumes from the sweep checkpoint; typed
  errors (``DeviceLostError``, ``TransferStallError``) classify what is a
  device-runtime loss versus an ordinary candidate failure.

No jax import at module scope: the whole point of the probe is deciding
whether touching the backend is safe, so the supervisor itself must load
without initializing it.
"""

from __future__ import annotations

import itertools
import json
import os
import subprocess
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence

from ..resilience import (CircuitBreaker, InjectedFault, maybe_inject,
                          record_failure)

# -- state machine states (also ProbeVerdict statuses) ----------------------
AVAILABLE = "available"
DEGRADED = "degraded"
OUTAGE = "outage"
_STATE_CODES = {AVAILABLE: 0, DEGRADED: 1, OUTAGE: 2}


class DeviceLostError(RuntimeError):
    """A device participating in the active mesh was lost mid-run."""


class TransferStallError(RuntimeError):
    """A host→device transfer chunk exceeded its deadline (hung link)."""


# --------------------------------------------------------------------------
# knobs (env-driven so params/runner ride them like meshParams does)
# --------------------------------------------------------------------------

def supervisor_enabled() -> bool:
    """Kill switch: TRANSMOGRIFAI_SUPERVISOR=0 (or --no-supervisor) turns
    off sweep recovery; probes stay callable (they are just subprocesses)."""
    return os.environ.get("TRANSMOGRIFAI_SUPERVISOR") != "0"


def probe_timeout_s() -> float:
    """Per-probe deadline (TRANSMOGRIFAI_PROBE_TIMEOUT_S; the legacy
    BENCH_PROBE_TIMEOUT_S is honored so round-5 operator scripts keep
    working; default 150s — the OUTAGE_r5 probes used 120s + margin)."""
    for var in ("TRANSMOGRIFAI_PROBE_TIMEOUT_S", "BENCH_PROBE_TIMEOUT_S"):
        v = os.environ.get(var)
        if v:
            try:
                return max(1.0, float(v))
            except ValueError:
                pass
    return 150.0


def probe_backoffs() -> List[float]:
    """Deterministic pre-probe backoff schedule in seconds
    (TRANSMOGRIFAI_PROBE_BACKOFFS / legacy BENCH_PROBE_BACKOFFS,
    default "0,45,120" — the round-5 schedule)."""
    for var in ("TRANSMOGRIFAI_PROBE_BACKOFFS", "BENCH_PROBE_BACKOFFS"):
        v = os.environ.get(var)
        if v:
            try:
                return [max(0.0, float(b)) for b in v.split(",") if b != ""]
            except ValueError:
                pass
    return [0.0, 45.0, 120.0]


def chunk_deadline_s() -> Optional[float]:
    """Per-chunk host→device transfer deadline
    (TRANSMOGRIFAI_CHUNK_DEADLINE_S; None/unset = no watchdog — the
    default, because a per-chunk watchdog thread costs ~50µs per chunk)."""
    v = os.environ.get("TRANSMOGRIFAI_CHUNK_DEADLINE_S")
    if not v:
        return None
    try:
        s = float(v)
    except ValueError:
        return None
    return s if s > 0 else None


def max_sweep_recoveries() -> int:
    """How many degrade-to-surviving-mesh resumes one sweep may attempt
    (TRANSMOGRIFAI_SWEEP_RECOVERIES, default 1); 0 when the supervisor is
    disabled — device-loss errors then propagate like any other."""
    if not supervisor_enabled():
        return 0
    try:
        return max(0, int(os.environ.get("TRANSMOGRIFAI_SWEEP_RECOVERIES",
                                         "1")))
    except ValueError:
        return 1


# --------------------------------------------------------------------------
# surviving-device tracking
# --------------------------------------------------------------------------

_SURVIVOR_LOCK = threading.Lock()
_DEVICE_CAP: Optional[int] = None    # None = all visible devices


def device_cap() -> Optional[int]:
    """Current surviving-device cap (None = no loss recorded)."""
    with _SURVIVOR_LOCK:
        return _DEVICE_CAP


def effective_device_count(n_visible: int) -> int:
    """Devices the mesh policy may use: the visible count clamped by the
    surviving-device cap (``maybe_data_mesh`` consults this, so the whole
    process degrades to the surviving mesh after ``mark_device_loss``)."""
    cap = device_cap()
    n = int(n_visible)
    return n if cap is None else max(1, min(n, cap))


def mark_device_loss(lost: int = 1) -> int:
    """Record the loss of ``lost`` device(s); returns the new cap.  jax's
    client cannot drop a device from an initialized backend, so the cap is
    how "the surviving mesh" is expressed: every subsequent
    ``maybe_data_mesh`` builds over the first ``cap`` devices only."""
    global _DEVICE_CAP
    with _SURVIVOR_LOCK:
        if _DEVICE_CAP is None:
            import jax   # lazy: only reached once a device already failed
            _DEVICE_CAP = len(jax.devices())
        _DEVICE_CAP = max(1, _DEVICE_CAP - max(1, int(lost)))
        cap = _DEVICE_CAP
    try:
        from ..telemetry import REGISTRY
        REGISTRY.gauge("supervisor.device_cap").set(cap)
    except Exception:  # noqa: BLE001 — bookkeeping must not mask the loss
        pass
    return cap


def reset_surviving_devices() -> None:
    """Clear the cap (tests; operator action after hardware recovers)."""
    global _DEVICE_CAP
    with _SURVIVOR_LOCK:
        _DEVICE_CAP = None


def is_device_loss(e: BaseException) -> bool:
    """Classify an exception as a device-runtime loss (vs an ordinary
    candidate/data failure).  Conservative on purpose: a compile error or
    OOM must keep its existing per-candidate degrade path — shrinking the
    mesh would not help and retrying the sweep would not converge."""
    if isinstance(e, (DeviceLostError, TransferStallError)):
        return True
    if type(e).__name__ == "HostLostError":
        return True   # hostgroup peer loss (name-matched: no circular import)
    s = str(e)
    if "supervisor.device_loss" in s or "supervisor.chunk_stall" in s \
            or "hostgroup.host_lost" in s:
        return True   # injected chaos markers (InjectedFault carries point)
    return ("UNAVAILABLE" in s or "DEVICE_LOST" in s
            or "device lost" in s.lower())


def note_sweep_device_loss(e: BaseException, *, attempt: int = 0,
                           stage: str = "validator") -> int:
    """One observable bundle per mid-sweep device loss: failure-log
    ``degraded``, ``supervisor.mesh_degrades_total`` counter, a
    ``supervisor.mesh_degrade`` telemetry event, and the shrunken
    surviving-device cap (returned)."""
    record_failure(stage, "degraded", e, point="supervisor.device_loss",
                   attempt=attempt, fallback="surviving-mesh resume")
    cap = mark_device_loss()
    try:
        from ..telemetry import REGISTRY, event
        REGISTRY.counter("supervisor.mesh_degrades_total").inc()
        event("supervisor.mesh_degrade", attempt=attempt, device_cap=cap,
              cause=f"{type(e).__name__}: {e}"[:200])
        from ..obsv import blackbox_note
        blackbox_note("supervisor.device_loss", attempt=attempt,
                      device_cap=cap,
                      cause=f"{type(e).__name__}: {e}"[:200])
    except Exception:  # noqa: BLE001
        pass
    return cap


# --------------------------------------------------------------------------
# supervised child processes (SIGTERM → SIGKILL escalation)
# --------------------------------------------------------------------------

@dataclass
class SupervisedResult:
    """Outcome of one supervised child run.  ``rc`` is 124 on deadline
    (the ``timeout(1)`` convention the scale-bench ladder already spoke);
    ``escalated`` means SIGTERM was ignored and SIGKILL reclaimed it."""

    rc: int
    stdout: str
    stderr: str
    wall_s: float
    timed_out: bool = False
    escalated: bool = False
    pid: int = 0


def run_supervised(cmd: Sequence[str], *, timeout_s: float,
                   grace_s: float = 10.0,
                   env: Optional[Dict[str, str]] = None,
                   cwd: Optional[str] = None,
                   traceparent: Optional[str] = None) -> SupervisedResult:
    """Run ``cmd`` under a SIGTERM→SIGKILL escalation deadline.

    On deadline: SIGTERM, wait ``grace_s``, then SIGKILL — the only kill
    that reliably works on a native-hung jax init (OUTAGE_r5.json).  The
    child is always reaped before returning (no zombies), and pipes are
    drained after the kill so a chatty child cannot deadlock the parent.

    The child inherits a trace context through ``TRANSMOGRIFAI_TRACEPARENT``
    (from ``traceparent`` when given, else the caller's ambient span) so a
    traced child nests under the triggering span across the process
    boundary; the run itself is recorded as a ``supervisor.child`` span."""
    from ..telemetry import (TRACEPARENT_ENV, TraceContext,
                             current_trace_context, span)
    parent_ctx = (TraceContext.parse(traceparent) if traceparent
                  else current_trace_context())
    child_ctx = parent_ctx.child() if parent_ctx else None
    env = dict(os.environ if env is None else env)
    if child_ctx is not None:
        env[TRACEPARENT_ENV] = child_ctx.to_traceparent()
    t0 = time.time()
    with span("supervisor.child", ctx=child_ctx,
              argv0=os.path.basename(str(cmd[0]))) as sp:
        p = subprocess.Popen(list(cmd), stdout=subprocess.PIPE,
                             stderr=subprocess.PIPE, text=True, env=env,
                             cwd=cwd, start_new_session=True)
        timed_out = escalated = False
        try:
            out, err = p.communicate(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            timed_out = True
            p.terminate()
            try:
                out, err = p.communicate(timeout=max(0.1, grace_s))
            except subprocess.TimeoutExpired:
                escalated = True
                p.kill()
                out, err = p.communicate()
        rc = 124 if timed_out else int(p.returncode)
        if sp is not None:
            sp.attrs.update(pid=p.pid, rc=rc, timed_out=timed_out,
                            escalated=escalated)
    return SupervisedResult(rc=rc, stdout=out or "", stderr=err or "",
                            wall_s=time.time() - t0, timed_out=timed_out,
                            escalated=escalated, pid=p.pid)


# --------------------------------------------------------------------------
# availability probes
# --------------------------------------------------------------------------

#: What the probe child actually does — ``jax.devices()`` (the call that
#: hangs during an outage) plus a tiny compiled matmul (the call that
#: proves dispatch works, not just enumeration).  The optional platform pin
#: mirrors conftest: a plain JAX_PLATFORMS env var can be overridden by the
#: container's sitecustomize, so the child re-pins via jax.config.
_PROBE_CHILD = """\
import json, os
import jax
_plat = os.environ.get("TRANSMOGRIFAI_PROBE_PLATFORM")
if _plat:
    jax.config.update("jax_platforms", _plat)
devs = jax.devices()
import jax.numpy as jnp
x = jnp.arange(256.0 * 256.0, dtype=jnp.float32).reshape(256, 256)
s = float(jnp.matmul(x, x).sum())
print(json.dumps({"platform": devs[0].platform,
                  "devices": [str(d) for d in devs],
                  "matmul_finite": s == s}))
"""

#: Chaos preludes prepended to the probe child — the injection surface the
#: train-side chaos harness and CI smoke use to fake the OUTAGE_r5 failure
#: modes in a real subprocess (``hang_ignore_sigterm`` is the mode plain
#: SIGTERM cannot kill; only the SIGKILL escalation reclaims it).
CHAOS_PRELUDES = {
    "die": "import sys\nsys.exit(17)\n",
    "hang": "import time\nwhile True:\n    time.sleep(3600)\n",
    "hang_ignore_sigterm": ("import signal, time\n"
                            "signal.signal(signal.SIGTERM, signal.SIG_IGN)\n"
                            "while True:\n    time.sleep(3600)\n"),
}


def _utc_hhmm(t: float) -> str:
    return time.strftime("%H:%M", time.gmtime(t))


@dataclass
class ProbeVerdict:
    """Structured availability verdict from a subprocess-isolated probe."""

    status: str                      # available | degraded | outage
    platform: Optional[str] = None
    device_count: int = 0
    devices: List[str] = field(default_factory=list)
    latency_s: float = 0.0
    cause: str = ""
    escalated: bool = False          # SIGKILL was needed to reclaim a probe
    attempts: List[Dict[str, Any]] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return self.status == AVAILABLE

    def to_json(self) -> Dict[str, Any]:
        return {"status": self.status, "platform": self.platform,
                "deviceCount": self.device_count, "devices": self.devices,
                "latencyS": round(self.latency_s, 3), "cause": self.cause,
                "escalated": self.escalated, "attempts": self.attempts}


def probe_devices(timeout_s: Optional[float] = None, *,
                  grace_s: float = 10.0, chaos: Optional[str] = None,
                  platform: Optional[str] = None,
                  expect_accelerator: bool = False,
                  key: Any = "probe") -> ProbeVerdict:
    """Probe device-runtime availability in a FRESH child process under the
    SIGTERM→SIGKILL escalation deadline.

    A hung init surfaces as ``status="outage", cause="hang"`` within
    ``timeout_s + grace_s`` instead of stalling the caller forever; a
    reachable runtime reports its platform + device inventory; a CPU
    fallback when ``expect_accelerator`` is set reads as ``degraded``
    (the honest label the round-5 bench fallback printed by hand).
    ``chaos`` prepends a :data:`CHAOS_PRELUDES` failure mode to the child."""
    timeout_s = probe_timeout_s() if timeout_s is None else float(timeout_s)
    t0 = time.time()
    try:
        maybe_inject("supervisor.probe", key=key)
    except InjectedFault as e:
        attempt = {"wall_s": 0.0, "result": "injected",
                   "from": _utc_hhmm(t0), "to": _utc_hhmm(t0)}
        return ProbeVerdict(status=OUTAGE, cause=str(e), attempts=[attempt])
    code = CHAOS_PRELUDES.get(chaos or "", "") + _PROBE_CHILD
    env = dict(os.environ)
    if platform:
        env["TRANSMOGRIFAI_PROBE_PLATFORM"] = platform
    r = run_supervised([sys.executable, "-c", code], timeout_s=timeout_s,
                       grace_s=grace_s, env=env)
    attempt: Dict[str, Any] = {"wall_s": round(r.wall_s, 1),
                               "from": _utc_hhmm(t0),
                               "to": _utc_hhmm(time.time())}
    if r.timed_out:
        attempt["result"] = "hang"
        return ProbeVerdict(status=OUTAGE, cause="hang",
                            latency_s=r.wall_s, escalated=r.escalated,
                            attempts=[attempt])
    if r.rc != 0:
        attempt["result"] = "error"
        attempt["tail"] = r.stderr.strip()[-300:]
        return ProbeVerdict(status=OUTAGE,
                            cause=f"probe child exited rc={r.rc}",
                            latency_s=r.wall_s, attempts=[attempt])
    line = next((ln for ln in reversed(r.stdout.splitlines())
                 if ln.startswith("{")), None)
    if not line:
        attempt["result"] = "no-verdict"
        return ProbeVerdict(status=DEGRADED,
                            cause="probe child printed no verdict line",
                            latency_s=r.wall_s, attempts=[attempt])
    info = json.loads(line)
    plat = info.get("platform")
    attempt["result"] = plat
    status = AVAILABLE
    cause = ""
    if expect_accelerator and plat == "cpu":
        status = DEGRADED
        cause = "accelerator expected but probe resolved cpu"
    return ProbeVerdict(status=status, platform=plat,
                        device_count=len(info.get("devices") or []),
                        devices=list(info.get("devices") or []),
                        latency_s=r.wall_s, cause=cause, attempts=[attempt])


def probe_with_backoff(timeout_s: Optional[float] = None,
                       backoffs: Optional[Sequence[float]] = None, *,
                       sleep: Callable[[float], None] = time.sleep,
                       key: Any = "probe",
                       **probe_kw) -> ProbeVerdict:
    """Retry :func:`probe_devices` on the deterministic backoff schedule
    until the runtime answers (available or degraded); the final verdict
    accumulates every attempt, so an outage verdict carries the full
    timeline for the outage record."""
    backoffs = list(probe_backoffs() if backoffs is None else backoffs)
    attempts: List[Dict[str, Any]] = []
    verdict = None
    for i, backoff_s in enumerate(backoffs or [0.0]):
        if backoff_s:
            sleep(backoff_s)
        verdict = probe_devices(timeout_s, key=f"{key}:{i}", **probe_kw)
        for a in verdict.attempts:
            attempts.append({**a, "every_s": backoff_s})
        if verdict.status != OUTAGE:
            break
    verdict.attempts = attempts
    try:
        from ..telemetry import REGISTRY
        REGISTRY.counter("supervisor.probes_total").inc(len(attempts))
        REGISTRY.gauge("supervisor.last_probe_latency_s").set(
            round(verdict.latency_s, 3))
    except Exception:  # noqa: BLE001
        pass
    return verdict


# --------------------------------------------------------------------------
# standardized outage records (the OUTAGE_r5.json shape, by code)
# --------------------------------------------------------------------------

#: The stable schema — key-for-key the shape of the hand-written
#: OUTAGE_r5.json, so dashboards/post-mortems parse both generations.
OUTAGE_RECORD_KEYS = ("what", "context", "probe", "timeline_utc",
                      "mitigations_landed_this_round", "will_update")

_PROBE_DESC = ("fresh-process `jax.devices()` + 256x256 matmul-sum under a "
               "SIGTERM->SIGKILL escalation deadline "
               "(parallel/supervisor.py probe_devices)")


def outage_timeline(attempts: Sequence[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Probe attempts → the ``timeline_utc`` entries of the record shape."""
    out = []
    for a in attempts:
        out.append({"from": a.get("from", ""), "to": a.get("to", ""),
                    "every_s": a.get("every_s", 0),
                    "result": a.get("result", "")})
    return out


def write_outage_record(path: str, *, what: str, context: str = "",
                        probe: str = _PROBE_DESC,
                        timeline: Optional[Sequence[Dict[str, Any]]] = None,
                        mitigations: Sequence[str] = (),
                        will_update: str = "",
                        blackbox: Optional[str] = None) -> Dict[str, Any]:
    """Atomically write one outage record in the OUTAGE_r5.json schema;
    returns the record dict.  When the training control plane has dumped a
    flight-recorder ``blackbox.json`` this run, the record points at it
    (additive ``blackbox`` key — the r5 key set stays intact otherwise)."""
    rec = {"what": what, "context": context, "probe": probe,
           "timeline_utc": list(timeline or []),
           "mitigations_landed_this_round": list(mitigations),
           "will_update": will_update}
    if blackbox is None:
        try:
            from ..obsv import last_blackbox_path
            blackbox = last_blackbox_path()
        except Exception:  # noqa: BLE001
            blackbox = None
    if blackbox:
        rec["blackbox"] = blackbox
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(rec, fh, indent=2)
    os.replace(tmp, path)
    return rec


def default_outage_path() -> Optional[str]:
    """Where unprompted outage records land: $TRANSMOGRIFAI_OUTAGE_DIR
    (one file per UTC day), else nowhere (None) — library code must never
    scribble into an unconfigured working directory."""
    d = os.environ.get("TRANSMOGRIFAI_OUTAGE_DIR")
    if not d:
        return None
    return os.path.join(d, time.strftime("OUTAGE_%Y%m%d.json", time.gmtime()))


def maybe_write_outage_record(*, what: str, context: str = "",
                              attempts: Sequence[Dict[str, Any]] = (),
                              mitigations: Sequence[str] = (),
                              will_update: str = "",
                              path: Optional[str] = None) -> Optional[str]:
    """The shared writer every outage site routes through (bench fallback,
    heartbeat trips, CI smoke): writes to ``path`` or the env-configured
    default; returns the path written, or None when no destination is
    configured (the caller's stdout record still happens)."""
    path = path or os.environ.get("BENCH_OUTAGE_RECORD") \
        or default_outage_path()
    if not path:
        return None
    try:
        write_outage_record(path, what=what, context=context,
                            timeline=outage_timeline(attempts),
                            mitigations=mitigations,
                            will_update=will_update)
    except Exception as e:  # noqa: BLE001 — the record is best-effort
        record_failure("supervisor", "swallowed", e,
                       point="supervisor.outage_record")
        return None
    return path


# --------------------------------------------------------------------------
# heartbeat supervision
# --------------------------------------------------------------------------

class Heartbeat:
    """Background device-runtime supervision: re-probe on a deterministic
    backoff schedule, feed a :class:`CircuitBreaker`, drive the
    AVAILABLE/DEGRADED/OUTAGE state machine.

    * probe ``available`` → breaker success; state AVAILABLE.
    * probe ``degraded`` (cpu fallback etc.) → breaker success (the runtime
      answered) but state DEGRADED.
    * probe ``outage`` → breaker failure; state DEGRADED until the breaker
      trips, OUTAGE once it opens.  The OUTAGE transition records an
      ``outage`` FailureLog action, bumps ``supervisor.outages_total`` and
      writes a standardized outage record; recovery records ``recovered``.

    The probe interval doubles per consecutive failure (``interval_s`` →
    ``max_interval_s``) and resets on success.  Every collaborator (probe
    callable, clock, breaker) is injectable, so the state machine tests run
    on a fake clock with zero subprocesses; ``tick()`` is the synchronous
    unit the thread loop repeats."""

    def __init__(self, probe: Optional[Callable[[], ProbeVerdict]] = None, *,
                 interval_s: float = 300.0, max_interval_s: float = 1800.0,
                 multiplier: float = 2.0,
                 breaker: Optional[CircuitBreaker] = None,
                 failure_threshold: int = 2, reset_timeout_s: float = 600.0,
                 clock: Callable[[], float] = time.monotonic,
                 outage_dir: Optional[str] = None,
                 context: str = "device-runtime heartbeat"):
        from ..telemetry import REGISTRY
        self._registry = REGISTRY
        self._probe = probe if probe is not None else (
            lambda: probe_devices(key="heartbeat"))
        self.interval_s = float(interval_s)
        self.max_interval_s = float(max_interval_s)
        self.multiplier = max(1.0, float(multiplier))
        self.breaker = breaker if breaker is not None else CircuitBreaker(
            "device_runtime", failure_threshold=failure_threshold,
            min_calls=max(2 * failure_threshold, 4),
            reset_timeout_s=reset_timeout_s, clock=clock,
            registry=self._registry)
        self.context = context
        self.outage_dir = (outage_dir
                           or os.environ.get("TRANSMOGRIFAI_OUTAGE_DIR"))
        self.state = AVAILABLE
        self.last_verdict: Optional[ProbeVerdict] = None
        self._consecutive_failures = 0
        self._ticks = 0
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._registry.gauge("supervisor.state", self.state_code)

    # -- inspection --------------------------------------------------------
    def state_code(self) -> int:
        return _STATE_CODES[self.state]

    def next_interval_s(self) -> float:
        """Deterministic backoff: interval × multiplier^consecutive-failures,
        capped at ``max_interval_s``."""
        with self._lock:
            n = self._consecutive_failures
        return min(self.max_interval_s,
                   self.interval_s * self.multiplier ** n)

    # -- one synchronous supervision step ----------------------------------
    def tick(self) -> ProbeVerdict:
        with self._lock:
            tick_no = self._ticks
            self._ticks += 1
        try:
            maybe_inject("supervisor.heartbeat", key=tick_no)
            v = self._probe()
        except InjectedFault as e:
            v = ProbeVerdict(status=OUTAGE, cause=str(e))
        except Exception as e:  # noqa: BLE001 — a broken probe IS an outage
            v = ProbeVerdict(status=OUTAGE,
                             cause=f"{type(e).__name__}: {e}")
        self.last_verdict = v
        self._registry.counter("supervisor.probes_total").inc()
        self._registry.gauge("supervisor.last_probe_latency_s").set(
            round(v.latency_s, 3))
        # advance the breaker's open→half-open edge lazily (same contract as
        # call sites using allow()): the heartbeat IS the recovery probe
        self.breaker.allow()
        if v.status == OUTAGE:
            self.breaker.record_failure(v.cause)
            with self._lock:
                self._consecutive_failures += 1
        else:
            self.breaker.record_success()
            with self._lock:
                self._consecutive_failures = 0
        if v.status == OUTAGE:
            tripped = self.breaker.current_state() != CircuitBreaker.CLOSED
            new = OUTAGE if tripped else DEGRADED
        elif v.status == DEGRADED:
            new = DEGRADED
        else:
            new = AVAILABLE
        if new != self.state:
            self._transition(new, v)
        return v

    def _transition(self, new: str, v: ProbeVerdict) -> None:
        old, self.state = self.state, new
        try:
            from ..telemetry import event
            event("supervisor.transition", from_state=old, to_state=new,
                  cause=(v.cause or v.status)[:200])
        except Exception:  # noqa: BLE001
            pass
        if new == OUTAGE:
            record_failure("supervisor", "outage", v.cause or "probe outage",
                           point="supervisor.heartbeat",
                           breaker=self.breaker.name)
            self._registry.counter("supervisor.outages_total").inc()
            try:
                from ..obsv import blackbox_note
                blackbox_note("supervisor.outage",
                              cause=(v.cause or v.status)[:200],
                              from_state=old)
            except Exception:  # noqa: BLE001
                pass
            maybe_write_outage_record(
                what="device runtime unavailable (heartbeat breaker open)",
                context=self.context, attempts=v.attempts,
                mitigations=("heartbeat degraded the process to the "
                             "surviving/CPU path; see failure log",),
                will_update="recovery transition appends to the failure log",
                path=(os.path.join(self.outage_dir,
                                   time.strftime("OUTAGE_%Y%m%d.json",
                                                 time.gmtime()))
                      if self.outage_dir else None))
        elif new == AVAILABLE:
            record_failure("supervisor", "recovered",
                           f"device runtime recovered from {old}",
                           point="supervisor.heartbeat")
        else:
            record_failure("supervisor", "degraded",
                           v.cause or "probe degraded",
                           point="supervisor.heartbeat")

    # -- background loop ---------------------------------------------------
    def start(self) -> "Heartbeat":
        with self._lock:
            if self._thread is not None:
                return self
            self._stop.clear()
            self._thread = threading.Thread(target=self._loop, daemon=True,
                                            name="supervisor-heartbeat")
            self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — supervision must not die
                pass
            self._stop.wait(self.next_interval_s())

    def stop(self, timeout_s: float = 5.0) -> None:
        self._stop.set()
        with self._lock:
            t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=timeout_s)


# monotone chunk sequence for streaming's chunk-stall injection keys: keys
# never repeat across sweep recovery attempts, so a sticky fail_keys entry
# kills the FIRST attempt's chunk and lets the resume stream cleanly
_CHUNK_SEQ = itertools.count()


def next_chunk_key() -> int:
    return next(_CHUNK_SEQ)
