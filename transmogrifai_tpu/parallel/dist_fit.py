"""Sharded fit kernels — the CV grid and stat reductions as single GSPMD
programs over the (data × model) mesh.

Design (SURVEY.md §2.6): the reference fans out k×Σ|grid| Spark jobs from a
JVM thread pool (OpValidator.scala:320-349).  Here the whole grid is ONE XLA
program: the data matrix is row-sharded over 'data' (gradients reduce via
psum-style collectives XLA inserts automatically), and the candidate axis is
``vmap``-ed then sharded over 'model' — every TPU core trains its slice of
candidates simultaneously on its slice of rows.
"""

from __future__ import annotations

import functools
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import DATA_AXIS, MODEL_AXIS, candidate_sharding, data_sharding, replicated_sharding


# --------------------------------------------------------------------------
# stat reductions (P2): one pass, collectives inserted by XLA
# --------------------------------------------------------------------------

def sharded_col_stats(X, y, mesh: Mesh):
    """Column moments + label correlation with rows sharded over 'data'
    (≙ SanityChecker colStats on executors, SanityChecker.scala:575)."""

    @functools.partial(
        jax.jit,
        in_shardings=(data_sharding(mesh, 2), data_sharding(mesh, 1)),
        out_shardings=replicated_sharding(mesh))
    def _stats(X, y):
        n = X.shape[0]
        mean = jnp.mean(X, axis=0)
        var = jnp.var(X, axis=0)
        ym = jnp.mean(y)
        yc = y - ym
        Xc = X - mean
        cov = yc @ Xc
        denom = jnp.sqrt(jnp.sum(Xc * Xc, axis=0) * jnp.sum(yc * yc))
        corr = cov / jnp.maximum(denom, 1e-12)
        return jnp.stack([mean, var, corr])

    return _stats(X, y)


# --------------------------------------------------------------------------
# grid-parallel logistic regression (P3)
# --------------------------------------------------------------------------

def _fista_logreg_fixed(X, y, l2, l1, n_iter: int):
    """Fixed-iteration FISTA for binary logistic (uniform work per candidate →
    perfectly vmappable).  Returns (coef [D], intercept)."""
    n, d = X.shape

    def obj_grad(w, b):
        logits = X @ w + b
        p = jax.nn.sigmoid(logits)
        g = (p - y) / n
        return X.T @ g + l2 * w, jnp.sum(g)

    # Lipschitz bound: 0.25 * max row-sum bound via matmul-free estimate
    L = 0.25 * jnp.sum(X * X) / n + l2
    step = 1.0 / jnp.maximum(L, 1e-12)

    def prox(u):
        return jnp.sign(u) * jnp.maximum(jnp.abs(u) - step * l1, 0.0)

    def body(_, state):
        w, b, zw, zb, t = state
        gw, gb = obj_grad(zw, zb)
        w_new = prox(zw - step * gw)
        b_new = zb - step * gb
        t_new = 0.5 * (1.0 + jnp.sqrt(1.0 + 4.0 * t * t))
        beta = (t - 1.0) / t_new
        return (w_new, b_new,
                w_new + beta * (w_new - w), b_new + beta * (b_new - b), t_new)

    z = jnp.zeros((d,), X.dtype)
    w, b, *_ = jax.lax.fori_loop(
        0, n_iter, body, (z, jnp.zeros((), X.dtype), z,
                          jnp.zeros((), X.dtype), jnp.ones((), X.dtype)))
    return w, b


@functools.lru_cache(maxsize=None)
def _grid_fitter(mesh: Mesh, n_iter: int):
    @functools.partial(
        jax.jit,
        in_shardings=(data_sharding(mesh, 2), data_sharding(mesh, 1),
                      candidate_sharding(mesh), candidate_sharding(mesh)),
        out_shardings=(candidate_sharding(mesh, 2), candidate_sharding(mesh, 1),
                       candidate_sharding(mesh, 1)))
    def fit(X, y, l2s, l1s):
        def one(l2, l1):
            w, b = _fista_logreg_fixed(X, y, l2, l1, n_iter)
            # train AuROC-surrogate: accuracy on the fly (cheap candidate score)
            pred = (X @ w + b) > 0
            acc = jnp.mean((pred == (y > 0.5)).astype(jnp.float32))
            return w, b, acc

        return jax.vmap(one)(l2s, l1s)

    return fit


def fit_logreg_grid_sharded(X, y, l2s, l1s, mesh: Mesh, n_iter: int = 50):
    """Train a whole regularisation grid in one sharded XLA program.
    Returns (coefs [G, D], intercepts [G], train accuracy [G])."""
    return _grid_fitter(mesh, n_iter)(
        jnp.asarray(X), jnp.asarray(y), jnp.asarray(l2s), jnp.asarray(l1s))


# --------------------------------------------------------------------------
# sharded tree ensembles (P1 × P3): rows over 'data', trees over 'model'
# --------------------------------------------------------------------------

def _mesh_platform(mesh: Mesh) -> str:
    return mesh.devices.flat[0].platform


def sharded_forest_fit(mesh: Mesh, *, task: str = "classification",
                       max_depth: int = 3, n_bins: int = 8,
                       features_per_node: "Optional[int]" = None):
    """Forest fit as one GSPMD program: the binned matrix + per-row stats are
    row-sharded over 'data' (the histogram one-hot contractions inside
    ``fit_tree`` contract the row axis, so XLA inserts the psum all-reduces —
    ≙ Spark's per-partition histogram merge), and the tree axis is vmapped then
    sharded over 'model'.  Returns the jitted fitter
    ``(B, splits, base_stats, boot [K, N], masks [K, D], keys [K])
    → TreeArrays [K, T]``.  ``features_per_node`` enables per-NODE feature
    subsetting from each tree's key (same semantics as the local fitters —
    per-TREE masks cannot learn cross-subset interactions).
    The class count is implied by the stats layout: ``base_stats`` is
    ``[count, onehot(y)]`` for classification, ``[count, y, y²]`` for
    regression (see ``fit_forest``)."""
    from ..models.trees import fit_tree, mxu_dtype_for

    impurity = "gini" if task == "classification" else "variance"
    hist_dtype = mxu_dtype_for(_mesh_platform(mesh))

    @functools.partial(
        jax.jit,
        in_shardings=(data_sharding(mesh, 2), replicated_sharding(mesh),
                      data_sharding(mesh, 2),
                      NamedSharding(mesh, P(MODEL_AXIS, DATA_AXIS)),
                      NamedSharding(mesh, P(MODEL_AXIS, None)),
                      NamedSharding(mesh, P(MODEL_AXIS))),
        out_shardings=NamedSharding(mesh, P(MODEL_AXIS)))
    def fit(B, splits, base_stats, boot, masks, keys):
        def one(bw, fm, k_):
            return fit_tree(B, splits, base_stats * bw[:, None], fm,
                            impurity=impurity, max_depth=max_depth,
                            n_bins=n_bins, min_instances=jnp.float32(1.0),
                            min_gain=jnp.float32(0.0), lam=jnp.float32(1.0),
                            hist_dtype=hist_dtype, node_feature_key=k_,
                            features_per_node=features_per_node)

        return jax.vmap(one)(boot, masks, keys)

    return fit


def sharded_gbt_round(mesh: Mesh, *, task: str = "classification",
                      max_depth: int = 3, n_bins: int = 8):
    """One boosting round over the mesh: grad/hess on row-sharded data, one
    tree fit (histogram reductions ride ICI psums), margin update in place.
    The round math is ``models.trees.gbt_round_body`` — the same function the
    local fitter jits — so weighting/hessian fixes propagate to both paths.
    Returns the jitted
    ``(B, splits, X, y, w0, margin, min_instances, min_gain, lam, eta)
    → (margin', TreeArrays)``."""
    from ..models.trees import gbt_round_body, mxu_dtype_for

    hist_dtype = mxu_dtype_for(_mesh_platform(mesh))
    repl = replicated_sharding(mesh)

    @functools.partial(
        jax.jit,
        in_shardings=(data_sharding(mesh, 2), repl,
                      data_sharding(mesh, 2), data_sharding(mesh, 1),
                      data_sharding(mesh, 1), data_sharding(mesh, 1),
                      repl, repl, repl, repl),
        out_shardings=(data_sharding(mesh, 1), repl))
    def round_fn(B, splits, X, y, w0, margin, min_instances, min_gain,
                 lam, eta):
        fmask = jnp.ones((B.shape[1],)) > 0
        return gbt_round_body(B, splits, X, y, w0, margin, fmask,
                              min_instances, min_gain, lam, eta, task=task,
                              max_depth=max_depth, n_bins=n_bins,
                              hist_dtype=hist_dtype)

    return round_fn


# --------------------------------------------------------------------------
# full sharded training step (used by __graft_entry__.dryrun_multichip)
# --------------------------------------------------------------------------

def sharded_train_step(mesh: Mesh, n_iter: int = 8):
    """One compiled end-to-end train step over the mesh:

      raw [N, D] rows (sharded over 'data')
        → standardize (psum moments)
        → sanity mask (variance filter as a static-shape multiply)
        → CV-grid logistic fit (vmapped over 'model'-sharded candidates)
        → per-candidate scores → argmax winner

    Mirrors OpWorkflow.train's layer flow with every Spark job fused into one
    XLA program.  Returns the jitted function.
    """

    @functools.partial(
        jax.jit,
        in_shardings=(data_sharding(mesh, 2), data_sharding(mesh, 1),
                      candidate_sharding(mesh), candidate_sharding(mesh)),
        out_shardings=replicated_sharding(mesh))
    def step(X, y, l2s, l1s):
        # feature engineering: standardize (collective moments over 'data')
        mean = jnp.mean(X, axis=0)
        var = jnp.var(X, axis=0)
        Xs = (X - mean) / jnp.sqrt(jnp.maximum(var, 1e-12))
        # sanity-checker-lite: zero out degenerate columns (static shape)
        keep = (var > 1e-10).astype(X.dtype)
        Xs = Xs * keep
        # grid fit over candidates
        def one(l2, l1):
            w, b = _fista_logreg_fixed(Xs, y, l2, l1, n_iter)
            p = jax.nn.sigmoid(Xs @ w + b)
            ls = -jnp.mean(y * jnp.log(p + 1e-9) + (1 - y) * jnp.log(1 - p + 1e-9))
            return w, b, ls

        ws, bs, losses = jax.vmap(one)(l2s, l1s)
        best = jnp.argmin(losses)
        return ws[best], bs[best], losses

    return step
