"""Cross-host resilient runtime: a supervised multi-process host group.

The reference's cross-executor story is Spark's driver/executor runtime —
lost executors are detected by driver heartbeats and their tasks re-run
elsewhere.  This module is that story for the jax_graft port: N ranked
worker *processes* (one per host; in CI, N local processes over the
multi-process CPU backend) under one supervising launcher, with host loss a
recoverable, observable event instead of a silent collective hang
(OUTAGE_r5's failure family at cross-host scope).

Four cooperating pieces:

* ``launch_hosts(cmd, n)`` — the launcher.  Spawns ``cmd`` once per rank
  under the ``run_supervised`` conventions (per-rank log/ready files in a
  run dir, ``start_new_session`` process groups, SIGTERM→grace→SIGKILL
  drain, zero orphans), pre-flighted by the subprocess device probe so an
  OUTAGE_r5-class native hang becomes a typed verdict before any rank
  exists.  Ranks find each other through ``TRANSMOGRIFAI_HOSTGROUP_*`` env
  vars (rank, world size, run dir, coordinator address, generation).

* rank-side init — ``maybe_init_hostgroup()`` is the one call worker code
  makes: it starts the host heartbeat, selects the CPU collectives backend
  (gloo) when needed, runs ``multihost.init_distributed`` against the
  group coordinator, and synchronizes on the ``init`` barrier before
  reporting ready.

* cross-host liveness — every rank heartbeats a per-rank file;
  :class:`HostLiveness` extends the supervisor's device-level
  AVAILABLE/DEGRADED/OUTAGE state machine to host granularity
  (``hostgroup.alive``/``hostgroup.state`` gauges, ``host_lost``/
  ``host_recovered`` failure-log actions, outage records through the
  shared OUTAGE_r5-schema writer).  ``barrier_sync(name, timeout_s)`` is
  the deadline-guarded rendezvous: a rank that never arrives surfaces as a
  typed :class:`HostLostError` on every survivor within the deadline — no
  Python-level collective can hang silently.  (Native collectives already
  in flight are reclaimed by the launcher's SIGTERM→SIGKILL drain, the
  only reclaim that works on hung native code.)

* lost-host recovery — when a rank dies (exit or stale heartbeat), the
  launcher writes an abort file (survivors' barriers trip immediately),
  drains the survivors, and relaunches the group at the shrunken world
  size with ``generation+1``.  Ranks resume from their durable
  ``SweepCheckpoint``s, so the relaunched sweep replays completed families
  instead of refitting them — winner parity with an uninterrupted run is
  asserted in ``scripts/ci_hostgroup_smoke.py``.

This module deliberately avoids importing jax at module scope (like
``supervisor``): the launcher itself must stay importable and responsive
even when the accelerator runtime is the thing that is wedged.
"""

from __future__ import annotations

import json
import os
import re
import signal
import socket
import subprocess
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence

from ..resilience import record_failure
from ..telemetry import (REGISTRY, TRACEPARENT_ENV, TraceContext,
                         current_trace_context, event, span)
from .supervisor import (AVAILABLE, DEGRADED, OUTAGE, _STATE_CODES,
                         maybe_write_outage_record, probe_devices,
                         supervisor_enabled)

# -- the rank-side contract: env vars the launcher exports ------------------
ENV_RANK = "TRANSMOGRIFAI_HOSTGROUP_RANK"
ENV_WORLD = "TRANSMOGRIFAI_HOSTGROUP_WORLD"
ENV_RUN_DIR = "TRANSMOGRIFAI_HOSTGROUP_RUN_DIR"
ENV_COORDINATOR = "TRANSMOGRIFAI_HOSTGROUP_COORDINATOR"
ENV_GENERATION = "TRANSMOGRIFAI_HOSTGROUP_GENERATION"
ENV_DISTRIBUTED = "TRANSMOGRIFAI_HOSTGROUP_DISTRIBUTED"

#: Exit code a rank uses when it aborted because a PEER was lost (barrier
#: abort / HostLostError / graceful preemption during a drain).  The
#: launcher must not count such an exit as a loss of that rank itself —
#: it stays in the relaunch set.  (BSD EX_TEMPFAIL: try again.)
EXIT_HOST_LOST = 75


class HostLostError(RuntimeError):
    """A peer rank was lost (never arrived at a barrier / abort posted).

    Typed so sweeps can classify it with ``supervisor.is_device_loss`` and
    so survivors exit with :data:`EXIT_HOST_LOST` instead of an anonymous
    traceback."""

    def __init__(self, message: str, *, missing: Sequence[int] = (),
                 barrier: str = ""):
        super().__init__(message)
        self.missing = list(missing)
        self.barrier = barrier


# --------------------------------------------------------------------------
# env knobs (params/runner ride these like supervisorParams does)
# --------------------------------------------------------------------------

def _float_env(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


def beat_interval_s() -> float:
    """Host heartbeat write period (TRANSMOGRIFAI_HOSTGROUP_BEAT_S)."""
    return max(0.05, _float_env("TRANSMOGRIFAI_HOSTGROUP_BEAT_S", 1.0))


def liveness_timeout_s() -> float:
    """Silence budget before a host counts as lost
    (TRANSMOGRIFAI_HOSTGROUP_LIVENESS_S)."""
    return max(0.1, _float_env("TRANSMOGRIFAI_HOSTGROUP_LIVENESS_S", 15.0))


def barrier_timeout_s() -> float:
    """Default ``barrier_sync`` deadline (TRANSMOGRIFAI_HOSTGROUP_BARRIER_S)."""
    return max(0.1, _float_env("TRANSMOGRIFAI_HOSTGROUP_BARRIER_S", 120.0))


def init_timeout_s() -> float:
    """``jax.distributed`` init watchdog (TRANSMOGRIFAI_HOSTGROUP_INIT_S)."""
    return max(1.0, _float_env("TRANSMOGRIFAI_HOSTGROUP_INIT_S", 60.0))


def hostgroup_env_present() -> bool:
    """Is this process a rank of a launched host group?"""
    return bool(os.environ.get(ENV_RANK)) and bool(os.environ.get(ENV_RUN_DIR))


def current_rank() -> int:
    try:
        return int(os.environ.get(ENV_RANK, "0"))
    except ValueError:
        return 0


def group_world_size() -> int:
    try:
        return max(1, int(os.environ.get(ENV_WORLD, "1")))
    except ValueError:
        return 1


def group_run_dir() -> Optional[str]:
    return os.environ.get(ENV_RUN_DIR) or None


def group_generation() -> int:
    try:
        return int(os.environ.get(ENV_GENERATION, "0"))
    except ValueError:
        return 0


# --------------------------------------------------------------------------
# shared-file plumbing (heartbeats, barriers, ready/done markers)
# --------------------------------------------------------------------------

def _atomic_write_json(path: str, payload: Dict[str, Any]) -> None:
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as fh:
        json.dump(payload, fh, default=str)
    os.replace(tmp, path)


def _read_json(path: str) -> Optional[Dict[str, Any]]:
    try:
        with open(path) as fh:
            return json.load(fh)
    except (OSError, ValueError):
        return None   # mid-replace / not yet written


def _hb_path(run_dir: str, rank: int) -> str:
    return os.path.join(run_dir, "hb", f"rank-{rank}.json")


def write_host_heartbeat(run_dir: str, rank: int, *, seq: int,
                         generation: int = 0, state: str = AVAILABLE,
                         wall: Optional[float] = None) -> None:
    _atomic_write_json(_hb_path(run_dir, rank), {
        "rank": int(rank), "pid": os.getpid(), "seq": int(seq),
        "generation": int(generation), "state": state,
        "wallS": float(time.time() if wall is None else wall)})


def read_host_heartbeat(run_dir: str, rank: int) -> Optional[Dict[str, Any]]:
    return _read_json(_hb_path(run_dir, rank))


def ready_path(run_dir: str, rank: int, generation: int = 0) -> str:
    return os.path.join(run_dir, "ready", f"rank-{rank}.gen{generation}.json")


def done_path(run_dir: str, rank: int, generation: int = 0) -> str:
    return os.path.join(run_dir, "done", f"rank-{rank}.gen{generation}.json")


def _abort_path(run_dir: str, generation: int) -> str:
    return os.path.join(run_dir, f"abort.gen{generation}.json")


def write_abort(run_dir: str, generation: int, lost: Sequence[int],
                reason: str) -> None:
    """Post a group abort: every survivor's ``barrier_sync`` raises a typed
    :class:`HostLostError` on its next poll instead of burning its full
    deadline."""
    _atomic_write_json(_abort_path(run_dir, generation), {
        "generation": int(generation), "lost": [int(r) for r in lost],
        "reason": reason, "wallS": time.time()})


def read_abort(run_dir: str, generation: int) -> Optional[Dict[str, Any]]:
    return _read_json(_abort_path(run_dir, generation))


class HostBeat:
    """Background writer of this rank's heartbeat file — the host-level
    analog of the supervisor's device heartbeat, minus the probe: liveness
    of the *process* is the signal, the launcher/rank-0 judges it."""

    def __init__(self, run_dir: str, rank: int, *,
                 interval_s: Optional[float] = None, generation: int = 0):
        self.run_dir = run_dir
        self.rank = rank
        self.generation = generation
        self.interval_s = interval_s if interval_s is not None \
            else beat_interval_s()
        self.state = AVAILABLE
        self.seq = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def beat(self) -> None:
        self.seq += 1
        write_host_heartbeat(self.run_dir, self.rank, seq=self.seq,
                             generation=self.generation, state=self.state)

    def start(self) -> "HostBeat":
        if self._thread is not None:
            return self
        self.beat()   # first beat synchronously: launcher sees us promptly

        def _loop():
            while not self._stop.wait(self.interval_s):
                try:
                    self.beat()
                except Exception as e:  # noqa: BLE001 — beats best-effort
                    record_failure("hostgroup", "swallowed", e,
                                   point="hostgroup.beat", rank=self.rank)

        self._thread = threading.Thread(target=_loop, daemon=True,
                                        name=f"hostgroup-beat-{self.rank}")
        self._thread.start()
        return self

    def stop(self, state: str = "stopped") -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2 * self.interval_s + 1.0)
            self._thread = None
        try:   # final beat records the terminal state for post-mortems
            self.state = state
            self.beat()
        except Exception:  # noqa: BLE001
            pass


class HostLiveness:
    """Host-level AVAILABLE/DEGRADED/OUTAGE state machine over the ranks'
    heartbeat files — the supervisor ``Heartbeat`` discipline lifted from
    device to host granularity.  ``tick()`` is the synchronous unit (fully
    fake-clock testable); transitions land as ``host_lost`` /
    ``host_recovered`` failure-log actions, ``hostgroup.alive`` /
    ``hostgroup.state`` gauges, and an OUTAGE_r5-schema record per loss."""

    def __init__(self, run_dir: str, world: int, *,
                 timeout_s: Optional[float] = None, generation: int = 0,
                 clock=time.time, outage_path: Optional[str] = None,
                 context: str = ""):
        self.run_dir = run_dir
        self.world = world
        self.generation = generation
        self.timeout_s = timeout_s if timeout_s is not None \
            else liveness_timeout_s()
        self.clock = clock
        self.outage_path = outage_path
        self.context = context or f"host group under {run_dir}"
        self.t0 = clock()
        self.last_wall: Dict[int, float] = {}
        self.status: Dict[int, Optional[bool]] = {r: None
                                                  for r in range(world)}
        self.losses: List[Dict[str, Any]] = []

    # -- one supervision step ---------------------------------------------
    def tick(self, ranks: Optional[Sequence[int]] = None) -> Dict[str, Any]:
        now = self.clock()
        watch = list(ranks) if ranks is not None else list(range(self.world))
        alive, lost = [], []
        for r in watch:
            hb = read_host_heartbeat(self.run_dir, r)
            if hb is not None and int(hb.get("generation", 0)) == \
                    self.generation:
                try:
                    self.last_wall[r] = float(hb.get("wallS", 0.0))
                except (TypeError, ValueError):
                    pass
            last = self.last_wall.get(r)
            silent = (now - last) if last is not None else (now - self.t0)
            is_alive = last is not None and silent <= self.timeout_s
            if last is None and silent <= self.timeout_s:
                alive.append(r)   # boot window: not yet beaten, in budget
                continue
            prev = self.status.get(r)
            if prev is not False and not is_alive:
                self._host_lost(r, silent_s=silent)
            elif prev is False and is_alive:
                self._host_recovered(r, silent_s=silent)
            self.status[r] = is_alive
            (alive if is_alive else lost).append(r)
        state = AVAILABLE if not lost else (OUTAGE if not alive else DEGRADED)
        REGISTRY.gauge("hostgroup.alive").set(len(alive))
        REGISTRY.gauge("hostgroup.state").set(_STATE_CODES[state])
        return {"state": state, "alive": alive, "lost": lost, "wall": now}

    def _host_lost(self, rank: int, *, silent_s: float) -> None:
        record_failure("hostgroup", "host_lost",
                       f"rank {rank} silent {silent_s:.1f}s "
                       f"(budget {self.timeout_s:g}s)",
                       point="hostgroup.liveness", rank=rank,
                       generation=self.generation)
        REGISTRY.counter("hostgroup.host_losses_total").inc()
        event("hostgroup.host_lost", rank=rank, silent_s=round(silent_s, 2),
              generation=self.generation)
        loss = {"rank": rank, "generation": self.generation,
                "silentS": round(silent_s, 2), "wall": self.clock()}
        self.losses.append(loss)
        maybe_write_outage_record(
            what=f"host rank {rank} lost: no heartbeat for "
                 f"{silent_s:.1f}s (budget {self.timeout_s:g}s)",
            context=self.context,
            attempts=[{"from": _iso(self.t0), "to": _iso(self.clock()),
                       "every_s": self.timeout_s,
                       "result": f"rank {rank} heartbeat silent; "
                                 f"host declared lost"}],
            mitigations=("survivors aborted via barrier deadline/abort file",
                         "launcher relaunches the group at the shrunken "
                         "world size, resuming sweep checkpoints"),
            will_update="on relaunch: hostgroup.relaunches_total increments "
                        "and a new generation boots",
            path=self.outage_path)

    def _host_recovered(self, rank: int, *, silent_s: float) -> None:
        record_failure("hostgroup", "host_recovered",
                       f"rank {rank} heartbeat resumed",
                       point="hostgroup.liveness", rank=rank,
                       generation=self.generation)
        REGISTRY.counter("hostgroup.host_recoveries_total").inc()
        event("hostgroup.host_recovered", rank=rank,
              generation=self.generation)


def _iso(wall: float) -> str:
    try:
        return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime(wall))
    except (OverflowError, OSError, ValueError):
        return str(wall)


# --------------------------------------------------------------------------
# deadline-guarded barrier
# --------------------------------------------------------------------------

def _barrier_file(run_dir: str, name: str, generation: int,
                  rank: int) -> str:
    safe = re.sub(r"[^A-Za-z0-9_.-]+", "_", name) or "barrier"
    return os.path.join(run_dir, "barrier",
                        f"{safe}.gen{generation}.rank{rank}.json")


def barrier_sync(name: str, timeout_s: Optional[float] = None, *,
                 rank: Optional[int] = None, world: Optional[int] = None,
                 run_dir: Optional[str] = None,
                 generation: Optional[int] = None, poll_s: float = 0.05,
                 clock=time.monotonic, sleep=time.sleep) -> float:
    """Rendezvous all ranks on ``name`` with a hard deadline.

    Arrival is a per-rank file under the run dir; a rank that never arrives
    surfaces on every waiting survivor as a typed :class:`HostLostError`
    naming the missing ranks within ``timeout_s`` — never a silent hang.
    A posted group abort (:func:`write_abort`) trips the barrier
    immediately, so survivors do not burn the full deadline once the
    launcher has already adjudicated the loss.  ``clock``/``sleep`` are
    injectable for fake-clock tests.  Returns the wait in (clock) seconds.
    """
    rank = current_rank() if rank is None else rank
    world = group_world_size() if world is None else world
    run_dir = group_run_dir() if run_dir is None else run_dir
    generation = group_generation() if generation is None else generation
    if run_dir is None:
        raise ValueError("barrier_sync needs a run_dir (not in a host group"
                         " and none passed)")
    timeout_s = barrier_timeout_s() if timeout_s is None else timeout_s
    _atomic_write_json(_barrier_file(run_dir, name, generation, rank),
                       {"rank": rank, "pid": os.getpid(),
                        "wallS": time.time()})
    t0 = clock()
    deadline = t0 + timeout_s
    with span("hostgroup.barrier", barrier=name, rank=rank, world=world,
              generation=generation, timeout_s=float(timeout_s)):
        while True:
            ab = read_abort(run_dir, generation)
            if ab is not None:
                missing = [int(r) for r in ab.get("lost", [])]
                raise HostLostError(
                    f"barrier {name!r} aborted: host(s) {missing} lost "
                    f"({ab.get('reason', 'no reason recorded')})",
                    missing=missing, barrier=name)
            missing = [r for r in range(world)
                       if not os.path.exists(
                           _barrier_file(run_dir, name, generation, r))]
            if not missing:
                waited = clock() - t0
                event("hostgroup.barrier_ok", barrier=name, rank=rank,
                      wait_s=round(waited, 3))
                return waited
            if clock() >= deadline:
                record_failure(
                    "hostgroup", "host_lost",
                    f"barrier {name!r} deadline {timeout_s:g}s: "
                    f"rank(s) {missing} never arrived",
                    point="hostgroup.barrier", rank=rank, barrier=name,
                    missing=",".join(map(str, missing)))
                REGISTRY.counter("hostgroup.barrier_timeouts_total").inc()
                raise HostLostError(
                    f"barrier {name!r} timed out after {timeout_s:g}s: "
                    f"rank(s) {missing} never arrived (world {world})",
                    missing=missing, barrier=name)
            sleep(poll_s)


# --------------------------------------------------------------------------
# rank-side context
# --------------------------------------------------------------------------

class HostGroup:
    """This rank's view of the group: identity, heartbeat, barriers and the
    ready/done markers the launcher (and smokes) consume."""

    def __init__(self, rank: int, world: int, run_dir: str, *,
                 generation: int = 0, coordinator: Optional[str] = None,
                 beat_interval: Optional[float] = None,
                 distributed: bool = False):
        self.rank = rank
        self.world = world
        self.run_dir = run_dir
        self.generation = generation
        self.coordinator = coordinator
        self.distributed = distributed
        self._beat = HostBeat(run_dir, rank, interval_s=beat_interval,
                              generation=generation)

    def barrier(self, name: str,
                timeout_s: Optional[float] = None) -> float:
        return barrier_sync(name, timeout_s, rank=self.rank,
                            world=self.world, run_dir=self.run_dir,
                            generation=self.generation)

    def mark_ready(self, extra: Optional[Dict[str, Any]] = None) -> None:
        _atomic_write_json(
            ready_path(self.run_dir, self.rank, self.generation),
            {"rank": self.rank, "pid": os.getpid(), "wallS": time.time(),
             "generation": self.generation,
             "distributed": self.distributed, **(extra or {})})

    def mark_done(self, payload: Optional[Dict[str, Any]] = None) -> None:
        _atomic_write_json(
            done_path(self.run_dir, self.rank, self.generation),
            {"rank": self.rank, "pid": os.getpid(), "wallS": time.time(),
             "generation": self.generation, **(payload or {})})

    def close(self, state: str = "stopped") -> None:
        self._beat.stop(state=state)


def maybe_init_hostgroup(*, distributed: Optional[bool] = None,
                         init_timeout: Optional[float] = None,
                         barrier_timeout: Optional[float] = None
                         ) -> Optional[HostGroup]:
    """Join the ambient host group, if this process is a rank of one.

    No-op (returns None) outside a launched group, so library code calls it
    unconditionally.  Inside one: starts the heartbeat, initializes
    ``jax.distributed`` against the group coordinator (CPU collectives
    backend selected first, so CI's multi-process CPU group runs real
    cross-process collectives), synchronizes the ``init`` barrier, and
    writes the ready marker the launcher's boot deadline watches.  Raises
    :class:`HostLostError` if a peer never reaches init — callers should
    exit :data:`EXIT_HOST_LOST` so the launcher keeps this rank in the
    relaunch set."""
    if not hostgroup_env_present():
        return None
    rank, world = current_rank(), group_world_size()
    run_dir, generation = group_run_dir(), group_generation()
    coordinator = os.environ.get(ENV_COORDINATOR) or None
    if distributed is None:
        distributed = os.environ.get(ENV_DISTRIBUTED, "1") != "0"
    distributed = bool(distributed and world > 1 and coordinator)
    hg = HostGroup(rank, world, run_dir, generation=generation,
                   coordinator=coordinator, distributed=distributed)
    hg._beat.start()
    REGISTRY.gauge("hostgroup.rank").set(rank)
    REGISTRY.gauge("hostgroup.world_size").set(world)
    REGISTRY.gauge("hostgroup.generation").set(generation)
    try:
        with span("hostgroup.init", rank=rank, world=world,
                  generation=generation, distributed=distributed):
            if distributed:
                from . import multihost
                multihost.ensure_cpu_collectives()
                multihost.init_distributed(
                    coordinator_address=coordinator, num_processes=world,
                    process_id=rank,
                    timeout_s=init_timeout if init_timeout is not None
                    else init_timeout_s())
            hg.barrier("init", timeout_s=barrier_timeout)
            hg.mark_ready()
    except BaseException:
        hg.close(state="init-failed")
        raise
    return hg


# --------------------------------------------------------------------------
# the launcher
# --------------------------------------------------------------------------

def _rank_obs_port(base: int, rank: int) -> int:
    """Control-plane port for ``rank`` given the configured base port.

    The launcher keeps ``base`` for its merged panel; rank ``r`` serves on
    ``base + 1 + r`` (rank 0 may share the launcher's host, so it cannot
    reuse ``base``).  ``launch_hosts`` exports the final per-rank value in
    the child env — ranks consume ``TRANSMOGRIFAI_OBS_PORT`` as-is and
    never offset themselves."""
    return int(base) + 1 + int(rank)


def _http_get(url: str, timeout_s: float = 1.0) -> Optional[str]:
    """Best-effort control-plane poll; None on any failure (a dead rank is
    a data point for ``hostgroup_rank_up``, not an error)."""
    import urllib.request
    try:
        with urllib.request.urlopen(url, timeout=timeout_s) as resp:
            return resp.read().decode("utf-8", "replace")
    except Exception:  # noqa: BLE001 — refused/timeout/garbage all mean down
        return None


def _start_merged_panel(base_port: int,
                        panel: Dict[str, Any]) -> Optional[Any]:
    """Launcher-side admin endpoint: polls every live rank's per-rank
    control plane at scrape time and re-serves ONE merged view —
    ``/metrics`` is the launcher registry plus ``hostgroup_rank_up{rank=}``
    plus every answering rank's exposition merged under a ``rank`` label
    (``merge_worker_metrics``); ``/statusz`` nests each rank's own statusz
    under ``ranks``.  ``panel`` is the launcher's mutable
    ``{"world", "generation"}`` state, updated per generation."""
    from ..obsv import maybe_start_obs_server, render_registry_metrics, \
        statusz_snapshot

    def _poll(endpoint: str) -> List[Any]:
        out = []
        for r in range(int(panel.get("world", 0))):
            body = _http_get(
                f"http://127.0.0.1:{_rank_obs_port(base_port, r)}"
                f"{endpoint}", timeout_s=panel.get("pollTimeoutS", 1.0))
            out.append((r, body))
        return out

    def merged_metrics() -> str:
        from ..serving.pool import merge_worker_metrics
        polled = _poll("/metrics")
        up = ["# HELP hostgroup_rank_up 1 if the rank's control plane "
              "answered the launcher's last poll",
              "# TYPE hostgroup_rank_up gauge"]
        texts = []
        for r, body in polled:
            up.append(f'hostgroup_rank_up{{rank="{r}"}} '
                      f'{1 if body is not None else 0}')
            if body is not None:
                texts.append((str(r), body))
        parts = [render_registry_metrics(), "\n".join(up) + "\n"]
        if texts:
            parts.append(merge_worker_metrics(texts, label="rank"))
        return "".join(parts)

    def merged_statusz() -> Dict[str, Any]:
        doc = statusz_snapshot()
        doc["role"] = "launcher"
        doc["world"] = int(panel.get("world", 0))
        doc["generation"] = int(panel.get("generation", 0))
        ranks: Dict[str, Any] = {}
        for r, body in _poll("/statusz"):
            if body is None:
                ranks[str(r)] = {"up": False}
                continue
            try:
                ranks[str(r)] = {"up": True, **json.loads(body)}
            except ValueError:
                ranks[str(r)] = {"up": True}
        doc["ranks"] = ranks
        return doc

    return maybe_start_obs_server(base_port, metrics_fn=merged_metrics,
                                  statusz_fn=merged_statusz)


def _free_port() -> int:
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    try:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]
    finally:
        s.close()


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def _signal_group(proc: subprocess.Popen, sig: int) -> None:
    """Signal the child's whole process group (it was started with
    ``start_new_session=True``), falling back to the pid."""
    try:
        os.killpg(os.getpgid(proc.pid), sig)
    except (OSError, ProcessLookupError):
        try:
            proc.send_signal(sig)
        except (OSError, ProcessLookupError):
            pass


def _drain(procs: Dict[int, subprocess.Popen], grace_s: float,
           poll_s: float = 0.05) -> Dict[int, int]:
    """SIGTERM→grace→SIGKILL every still-running child; reap all.  The
    same escalation ``run_supervised`` applies, across the group — zero
    orphans is the postcondition."""
    for proc in procs.values():
        if proc.poll() is None:
            _signal_group(proc, signal.SIGTERM)
    deadline = time.monotonic() + max(0.0, grace_s)
    while time.monotonic() < deadline and \
            any(p.poll() is None for p in procs.values()):
        time.sleep(poll_s)
    escalated = [r for r, p in procs.items() if p.poll() is None]
    for r in escalated:
        _signal_group(procs[r], signal.SIGKILL)
        record_failure("hostgroup", "escalated",
                       f"rank {r} ignored SIGTERM for {grace_s:g}s",
                       point="hostgroup.drain", rank=r)
    rcs = {}
    for r, p in procs.items():
        try:
            rcs[r] = p.wait(timeout=10.0)
        except subprocess.TimeoutExpired:   # unkillable (D-state); record
            record_failure("hostgroup", "swallowed",
                           f"rank {r} survived SIGKILL reap window",
                           point="hostgroup.drain", rank=r)
            rcs[r] = -signal.SIGKILL
    return rcs


@dataclass
class HostGroupResult:
    """Outcome of one ``launch_hosts`` supervision: per-generation world
    sizes, every loss event, the final ranks' exit codes."""

    ok: bool
    world_size: int
    final_world: int
    generations: int
    relaunches: int
    run_dir: str
    wall_s: float
    losses: List[Dict[str, Any]] = field(default_factory=list)
    rank_rcs: Dict[int, Optional[int]] = field(default_factory=dict)
    preflight: Optional[Dict[str, Any]] = None
    reason: str = ""

    def to_json(self) -> Dict[str, Any]:
        return {"ok": self.ok, "worldSize": self.world_size,
                "finalWorld": self.final_world,
                "generations": self.generations,
                "relaunches": self.relaunches, "runDir": self.run_dir,
                "wallS": round(self.wall_s, 2), "losses": self.losses,
                "rankRcs": {str(k): v for k, v in self.rank_rcs.items()},
                "preflight": self.preflight, "reason": self.reason}


def launch_hosts(cmd: Sequence[str], hosts: int, *,
                 run_dir: Optional[str] = None,
                 env: Optional[Dict[str, str]] = None,
                 boot_timeout: float = 240.0,
                 beat_interval: Optional[float] = None,
                 liveness_timeout: Optional[float] = None,
                 grace_s: float = 15.0, max_relaunches: int = 1,
                 poll_s: float = 0.2, preflight: Optional[bool] = None,
                 distributed: bool = True,
                 coordinator_host: str = "127.0.0.1") -> HostGroupResult:
    """Run ``cmd`` as an ``hosts``-rank supervised group; recover host loss.

    Every generation: pick a fresh coordinator port, spawn one ranked child
    per host (rank identity via ``TRANSMOGRIFAI_HOSTGROUP_*``; one child
    trace context per rank so all spans share the launcher's trace id),
    wait for the per-rank ready files under ``boot_timeout``, then monitor
    child liveness (process exit + heartbeat staleness).  On a loss: post
    the group abort, write the OUTAGE_r5-schema record, drain survivors
    under SIGTERM→SIGKILL, and — budget permitting — relaunch at the
    shrunken world size with ``generation+1`` so ranks resume their sweep
    checkpoints.  Returns when a generation completes cleanly (every rank
    exits 0) or the relaunch budget is exhausted; zero children survive
    this call in any outcome."""
    if hosts < 1:
        raise ValueError(f"hosts must be >= 1, got {hosts}")
    cmd = list(cmd)
    if run_dir is None:
        import tempfile
        run_dir = tempfile.mkdtemp(prefix="hostgroup-")
    run_dir = os.path.abspath(run_dir)
    os.makedirs(run_dir, exist_ok=True)
    liveness_budget = liveness_timeout if liveness_timeout is not None \
        else liveness_timeout_s()
    t_start = time.monotonic()
    result = HostGroupResult(ok=False, world_size=hosts, final_world=hosts,
                             generations=0, relaunches=0, run_dir=run_dir,
                             wall_s=0.0)

    # pre-flight: the PR-11 subprocess probe — a wedged accelerator runtime
    # (the OUTAGE_r5 native hang) becomes a typed verdict BEFORE any rank
    # exists, instead of N ranks hanging in init
    if preflight is None:
        preflight = supervisor_enabled()
    if preflight:
        verdict = probe_devices(key="hostgroup-preflight")
        result.preflight = verdict.to_json()
        if verdict.status == OUTAGE:
            result.reason = (f"preflight probe: {verdict.status} "
                             f"({verdict.cause})")
            maybe_write_outage_record(
                what="host group launch aborted by pre-flight probe "
                     f"({verdict.cause})",
                context=f"launch_hosts(hosts={hosts}) under {run_dir}",
                attempts=verdict.attempts,
                mitigations=("typed verdict before any rank spawned; "
                             "no stuck multi-process init",),
                will_update="on operator action; relaunch re-probes",
                path=os.path.join(run_dir, "OUTAGE_hostgroup_preflight.json"))
            result.wall_s = time.monotonic() - t_start
            return result

    parent_ctx = current_trace_context() or TraceContext.new()
    base_env = dict(os.environ)
    if env:
        base_env.update({str(k): str(v) for k, v in env.items()})
    # children must resolve the package wherever the launcher did
    base_env["PYTHONPATH"] = _repo_root() + (
        os.pathsep + base_env["PYTHONPATH"]
        if base_env.get("PYTHONPATH") else "")
    # every rank shares the launcher's compiled-program registry (and its
    # managed compile cache): rank 0's publishes warm ranks 1..N-1, and a
    # relaunch after a lost host resumes without re-paying compiles
    from ..aot_registry import managed_compile_cache, registry_root
    _reg = registry_root()
    if _reg:
        base_env.setdefault("TRANSMOGRIFAI_AOT_REGISTRY", _reg)
    _cache = managed_compile_cache()
    if _cache:
        base_env.setdefault("TRANSMOGRIFAI_COMPILE_CACHE", _cache)

    # training control plane: when an obs port is configured the launcher
    # keeps the base port for the merged rank panel and deals each child
    # rank its own port below (base+1+rank)
    from ..obsv import (FlightRecorder, active_recorder, blackbox_note,
                        install_recorder, obs_port_from_env)
    obs_base = obs_port_from_env()
    panel_state: Dict[str, Any] = {"world": hosts, "generation": 0}
    obs_panel = _start_merged_panel(obs_base, panel_state) \
        if obs_base else None
    # the launcher is the process that adjudicates host loss, so it needs
    # its own flight recorder for the per-generation loss dump (ranks each
    # carry theirs; a SIGKILLed rank writes nothing)
    own_recorder = None
    if obs_base and active_recorder() is None:
        own_recorder = install_recorder(FlightRecorder())

    world = hosts
    generation = 0
    procs: Dict[int, subprocess.Popen] = {}
    logs: List[Any] = []
    try:
        while True:
            result.generations = generation + 1
            result.final_world = world
            panel_state["world"] = world
            panel_state["generation"] = generation
            REGISTRY.gauge("hostgroup.world_size").set(world)
            REGISTRY.gauge("hostgroup.generation").set(generation)
            port = _free_port()
            coordinator = f"{coordinator_host}:{port}"
            _atomic_write_json(os.path.join(run_dir, "world.json"),
                               {"worldSize": world, "generation": generation,
                                "coordinator": coordinator,
                                "traceId": parent_ctx.trace_id})
            procs = {}
            with span("hostgroup.generation", generation=generation,
                      world=world):
                for rank in range(world):
                    child_env = dict(base_env)
                    child_env.update({
                        ENV_RANK: str(rank), ENV_WORLD: str(world),
                        ENV_RUN_DIR: run_dir,
                        ENV_GENERATION: str(generation),
                        ENV_COORDINATOR: coordinator,
                        ENV_DISTRIBUTED: "1" if distributed else "0",
                        TRACEPARENT_ENV:
                            parent_ctx.child().to_traceparent()})
                    if obs_base:
                        child_env["TRANSMOGRIFAI_OBS_PORT"] = \
                            str(_rank_obs_port(obs_base, rank))
                    if beat_interval is not None:
                        child_env["TRANSMOGRIFAI_HOSTGROUP_BEAT_S"] = \
                            str(beat_interval)
                    log_fh = open(os.path.join(run_dir,
                                               f"rank-{rank}.log"), "ab")
                    logs.append(log_fh)
                    procs[rank] = subprocess.Popen(
                        cmd, stdout=log_fh, stderr=subprocess.STDOUT,
                        env=child_env, start_new_session=True)
                    event("hostgroup.spawn", rank=rank, pid=procs[rank].pid,
                          generation=generation)

                outcome = _supervise_generation(
                    procs, run_dir, world, generation,
                    boot_timeout=boot_timeout,
                    liveness_budget=liveness_budget, grace_s=grace_s,
                    poll_s=poll_s)
            result.rank_rcs = {r: p.poll() for r, p in procs.items()}
            if outcome["completed"]:
                result.ok = True
                result.reason = "completed"
                REGISTRY.gauge("hostgroup.state").set(
                    _STATE_CODES[AVAILABLE])
                return result
            result.losses.extend(outcome["losses"])
            new_world = world - len(outcome["losses"])
            if new_world >= 1 and result.relaunches < max_relaunches:
                result.relaunches += 1
                blackbox_note("hostgroup.relaunch",
                              generation=generation + 1, world=new_world)
                REGISTRY.counter("hostgroup.relaunches_total").inc()
                record_failure(
                    "hostgroup", "relaunched",
                    f"generation {generation} lost "
                    f"{len(outcome['losses'])} host(s); relaunching at "
                    f"world={new_world}",
                    point="hostgroup.launch", generation=generation,
                    world=new_world)
                event("hostgroup.relaunch", generation=generation + 1,
                      world=new_world)
                world = new_world
                generation += 1
                continue
            result.reason = (f"host loss at generation {generation} "
                             f"(survivors {new_world}, relaunch budget "
                             f"{max_relaunches} spent)")
            return result
    finally:
        if own_recorder is not None:
            install_recorder(None)
        if obs_panel is not None:
            obs_panel.stop()
        # zero orphans, in every outcome — kill anything still breathing
        stragglers = {r: p for r, p in procs.items() if p.poll() is None}
        if stragglers:
            _drain(stragglers, grace_s=0.0)
        for fh in logs:
            try:
                fh.close()
            except OSError:
                pass
        result.wall_s = time.monotonic() - t_start
        _atomic_write_json(os.path.join(run_dir, "result.json"),
                           result.to_json())


def _supervise_generation(procs: Dict[int, subprocess.Popen], run_dir: str,
                          world: int, generation: int, *,
                          boot_timeout: float, liveness_budget: float,
                          grace_s: float, poll_s: float) -> Dict[str, Any]:
    """Boot-wait + monitor one generation.  Returns ``{"completed": bool,
    "losses": [...]}`` — on loss, the abort is posted and every survivor
    drained before returning."""
    liveness = HostLiveness(
        run_dir, world, timeout_s=max(liveness_budget, boot_timeout),
        generation=generation, context=f"launch_hosts generation "
                                       f"{generation} under {run_dir}",
        outage_path=os.path.join(
            run_dir, f"OUTAGE_hostgroup_gen{generation}.json"))
    boot_deadline = time.monotonic() + boot_timeout
    booted = False
    completed: set = set()
    losses: List[Dict[str, Any]] = []

    def _lose(rank: int, rc: Optional[int], kind: str) -> None:
        last = liveness.last_wall.get(rank)
        silent = (time.time() - last) if last else None
        losses.append({"rank": rank, "generation": generation, "rc": rc,
                       "kind": kind,
                       "silentS": round(silent, 2) if silent else None})
        record_failure("hostgroup", "host_lost",
                       f"rank {rank} {kind} (rc={rc}) at generation "
                       f"{generation}",
                       point="hostgroup.launch", rank=rank, rc=rc,
                       kind=kind, generation=generation)
        REGISTRY.counter("hostgroup.host_losses_total").inc()
        event("hostgroup.host_lost", rank=rank, rc=rc, kind=kind,
              generation=generation)

    while True:
        now = time.monotonic()
        abort_posted = read_abort(run_dir, generation) is not None
        for rank, proc in procs.items():
            rc = proc.poll()
            if rc is None or rank in completed or \
                    any(l["rank"] == rank for l in losses):
                continue
            if rc == 0:
                completed.add(rank)
            elif rc == EXIT_HOST_LOST and abort_posted:
                pass   # survivor aborting on a peer loss we adjudicated
            else:
                _lose(rank, rc, "exit")
        if not booted:
            ready = [r for r in range(world)
                     if os.path.exists(ready_path(run_dir, r, generation))]
            if len(ready) == world:
                booted = True
                liveness.timeout_s = liveness_budget
                event("hostgroup.booted", generation=generation,
                      world=world)
            elif now >= boot_deadline and not losses:
                # the OUTAGE_r5 shape at group scope: rank(s) wedged before
                # ready — reclaim them (SIGTERM→SIGKILL) and call it a loss
                for rank in range(world):
                    if rank not in ready and procs[rank].poll() is None:
                        _drain({rank: procs[rank]}, grace_s)
                        _lose(rank, procs[rank].poll(), "boot-hang")
                if not losses:   # every laggard exited 0?? treat as hang
                    _lose(min(r for r in range(world) if r not in ready),
                          None, "boot-hang")
        if booted and not losses:
            st = liveness.tick(ranks=[r for r in range(world)
                                      if r not in completed])
            for rank in st["lost"]:
                proc = procs.get(rank)
                if proc is not None and proc.poll() is None:
                    # alive but silent past budget: hung — reclaim it
                    _drain({rank: proc}, grace_s)
                    _lose(rank, proc.poll(), "hang")
        if losses:
            lost_ranks = [l["rank"] for l in losses]
            write_abort(run_dir, generation, lost_ranks,
                        reason=f"rank(s) {lost_ranks} lost "
                               f"({losses[0]['kind']})")
            REGISTRY.gauge("hostgroup.state").set(_STATE_CODES[
                OUTAGE if len(lost_ranks) >= world else DEGRADED])
            # the launcher is the process that adjudicated the loss, so it
            # dumps the flight recorder here — BEFORE the outage record,
            # which then references the dump (a SIGKILLed rank writes
            # nothing, and a survivor wedged in a dead collective may never
            # reach its own except path)
            from ..obsv import blackbox_note, dump_blackbox
            for l in losses:
                blackbox_note("hostgroup.host_lost", loss=dict(l))
            dump_blackbox(
                reason=f"HostLostError: rank(s) {lost_ranks} lost "
                       f"({losses[0]['kind']}, rc={losses[0]['rc']})",
                path=os.path.join(run_dir,
                                  f"blackbox-launcher-gen{generation}.json"))
            maybe_write_outage_record(
                what=f"host(s) {lost_ranks} lost at generation "
                     f"{generation} (world {world}): "
                     f"{losses[0]['kind']}, rc={losses[0]['rc']}",
                context=f"launch_hosts generation {generation} under "
                        f"{run_dir}",
                attempts=[{"from": _iso(time.time()), "to": _iso(time.time()),
                           "every_s": poll_s,
                           "result": f"rank {l['rank']} {l['kind']} "
                                     f"(rc={l['rc']})"} for l in losses],
                mitigations=("abort posted: survivors' barriers raise typed "
                             "HostLostError instead of hanging",
                             "survivors drained under SIGTERM->SIGKILL",
                             "relaunch at shrunken world resumes sweep "
                             "checkpoints"),
                will_update="hostgroup.relaunches_total increments when the "
                            "shrunken generation boots",
                path=liveness.outage_path)
            _drain(procs, grace_s)
            return {"completed": False, "losses": losses}
        if len(completed) == world:
            return {"completed": True, "losses": []}
        time.sleep(poll_s)
