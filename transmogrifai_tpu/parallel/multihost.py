"""Multi-host initialization — the DCN story (SURVEY §2.6 P7).

The reference's cross-executor traffic rides Spark's netty shuffle; here
cross-HOST traffic is jax's distributed runtime: every host calls
``init_distributed()`` (coordinator address + process id, or nothing under a
supported cluster environment), after which ``jax.devices()`` spans all hosts
and the SAME mesh/sharding code in this package rides ICI within a slice and
DCN across slices — no separate transport layer exists or is needed.

Typical launch (one line per host)::

    from transmogrifai_tpu.parallel import init_distributed, make_mesh
    init_distributed()          # auto-detected under TPU pods / GKE
    mesh = make_mesh()          # all hosts' devices, rows over 'data'

Single-process runs are a no-op, so library code can call this
unconditionally.
"""

from __future__ import annotations

from typing import Optional

import jax

from ..resilience import maybe_inject, record_failure, run_with_deadline


#: Env vars that name a coordinator / TPU-pod topology outright: their
#: presence alone is enough to attempt auto-init.
_COORDINATOR_ENV_VARS = (
    "COORDINATOR_ADDRESS", "JAX_COORDINATOR_ADDRESS",
    "MEGASCALE_COORDINATOR_ADDRESS", "TPU_WORKER_HOSTNAMES",
    "CLOUD_TPU_TASK_ID",
)

#: Env vars that carry the scheduler's world size.  A bare job id
#: (SLURM_JOB_ID) is NOT here on purpose: a single-node SLURM job used to
#: trip auto-init on it and "degrade" to single-host every run — only a
#: world size > 1 means there are actually peers to rendezvous with.
_WORLD_SIZE_ENV_VARS = (
    "SLURM_NTASKS", "SLURM_NPROCS", "OMPI_COMM_WORLD_SIZE", "PMI_SIZE",
)

# kept for back-compat introspection (tests/dashboards list it)
_CLUSTER_ENV_VARS = _COORDINATOR_ENV_VARS + _WORLD_SIZE_ENV_VARS


def _world_size_env() -> int:
    """Largest world size any scheduler env var claims (0 when none do)."""
    import os
    n = 0
    for v in _WORLD_SIZE_ENV_VARS:
        raw = os.environ.get(v)
        if not raw:
            continue
        try:
            n = max(n, int(raw))
        except ValueError:
            continue
    return n


def _cluster_env_present() -> bool:
    """Only auto-detect when the environment names a coordinator or claims
    a world size > 1 — a lone SLURM_JOB_ID (single-node job) must not
    trigger an observably-failing distributed init attempt."""
    import os
    if any(os.environ.get(v) for v in _COORDINATOR_ENV_VARS):
        return True
    return _world_size_env() > 1


def init_distributed(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None,
                     timeout_s: Optional[float] = None) -> bool:
    """Initialize jax's distributed runtime (idempotent, single-process safe).

    Returns True when a multi-process runtime is active after the call.
    Auto-detection only runs under a recognizable cluster environment (TPU
    pod / GKE / SLURM / MPI env vars) — probing jax's auto-detect on plain
    single-host machines can hard-abort the process, so without a coordinator
    and without cluster env vars this is a clean no-op.

    ``timeout_s`` runs the init under a watchdog: the round-5 outage showed
    it can HANG in native code with no error raised (OUTAGE_r5.json), and a
    hang must surface as ``WatchdogTimeout`` — raised for an explicit
    coordinator request, recorded in the failure log and degraded to
    single-host for auto-detection.

    .. note:: the watchdog can only *abandon* a hung native init thread, it
       cannot reclaim it (the thread leaks; ``watchdog.abandoned_total``
       counts them).  Callers that need the hang actually killed must
       pre-flight with the subprocess-isolated
       ``parallel.supervisor.probe_devices`` — a child process under
       SIGTERM→SIGKILL escalation is the only reclaim that works.

    Emits a ``multihost.init`` telemetry span around the attempt and sets
    the ``multihost.process_count`` / ``multihost.initialized`` gauges, so
    a degraded-to-single-host run is visible on dashboards and not just in
    the failure log.
    """
    from ..telemetry import REGISTRY, span
    already = getattr(jax.distributed, "is_initialized", None)
    if already is not None and already():
        REGISTRY.gauge("multihost.initialized").set(1)
        REGISTRY.gauge("multihost.process_count").set(jax.process_count())
        return jax.process_count() > 1
    if coordinator_address is None and not _cluster_env_present():
        return False
    try:
        with span("multihost.init",
                  coordinator=coordinator_address or "auto",
                  requested_processes=int(num_processes or 0),
                  timeout_s=float(timeout_s or 0)):
            maybe_inject("multihost.init", key=coordinator_address or "auto")
            run_with_deadline(
                jax.distributed.initialize, timeout_s,
                coordinator_address=coordinator_address,
                num_processes=num_processes, process_id=process_id,
                description="jax.distributed.initialize")
    except Exception as e:  # noqa: BLE001
        REGISTRY.gauge("multihost.initialized").set(0)
        # known truth on EVERY exit path: init failed, this process is
        # single — a stale >1 from a prior run must not survive the raise
        REGISTRY.gauge("multihost.process_count").set(1)
        if coordinator_address is not None:
            # an EXPLICIT multi-host request that fails must not silently
            # degrade to single-host (every host would train divergently)
            raise
        # auto-detected cluster env but init failed: degrade to single-host,
        # observably — exactly the demotion the round-5 probes did by hand
        record_failure("multihost.init_distributed", "degraded", e,
                       point="multihost.init", fallback="single-host")
        return False
    REGISTRY.gauge("multihost.initialized").set(1)
    REGISTRY.gauge("multihost.process_count").set(jax.process_count())
    return jax.process_count() > 1


def ensure_cpu_collectives(implementation: str = "gloo") -> bool:
    """Select a cross-process collectives backend for the CPU client.

    jax's default CPU client has none: a multi-process CPU group can
    ``init_distributed`` fine and then fail every computation over a
    cross-process array with "Multiprocess computations aren't implemented
    on the CPU backend".  Selecting gloo *before the backend first
    initializes* makes the 2-process CI host group run real cross-process
    collectives.  Best-effort: harmless (and a recorded no-op) on builds
    without the option or after the backend is already live."""
    from ..telemetry import REGISTRY
    try:
        jax.config.update("jax_cpu_collectives_implementation",
                          implementation)
    except Exception as e:  # noqa: BLE001 — option absent / backend live
        record_failure("multihost.cpu_collectives", "swallowed", e,
                       point="multihost.cpu_collectives",
                       implementation=implementation)
        REGISTRY.gauge("multihost.cpu_collectives").set(0)
        return False
    REGISTRY.gauge("multihost.cpu_collectives").set(1)
    return True


def is_multihost() -> bool:
    return jax.process_count() > 1
