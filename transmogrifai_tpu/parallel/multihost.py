"""Multi-host initialization — the DCN story (SURVEY §2.6 P7).

The reference's cross-executor traffic rides Spark's netty shuffle; here
cross-HOST traffic is jax's distributed runtime: every host calls
``init_distributed()`` (coordinator address + process id, or nothing under a
supported cluster environment), after which ``jax.devices()`` spans all hosts
and the SAME mesh/sharding code in this package rides ICI within a slice and
DCN across slices — no separate transport layer exists or is needed.

Typical launch (one line per host)::

    from transmogrifai_tpu.parallel import init_distributed, make_mesh
    init_distributed()          # auto-detected under TPU pods / GKE
    mesh = make_mesh()          # all hosts' devices, rows over 'data'

Single-process runs are a no-op, so library code can call this
unconditionally.
"""

from __future__ import annotations

from typing import Optional

import jax

from ..resilience import maybe_inject, record_failure, run_with_deadline


_CLUSTER_ENV_VARS = (
    "COORDINATOR_ADDRESS", "JAX_COORDINATOR_ADDRESS",
    "MEGASCALE_COORDINATOR_ADDRESS", "TPU_WORKER_HOSTNAMES",
    "CLOUD_TPU_TASK_ID", "SLURM_JOB_ID", "OMPI_COMM_WORLD_SIZE",
)


def _cluster_env_present() -> bool:
    import os
    return any(os.environ.get(v) for v in _CLUSTER_ENV_VARS)


def init_distributed(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None,
                     timeout_s: Optional[float] = None) -> bool:
    """Initialize jax's distributed runtime (idempotent, single-process safe).

    Returns True when a multi-process runtime is active after the call.
    Auto-detection only runs under a recognizable cluster environment (TPU
    pod / GKE / SLURM / MPI env vars) — probing jax's auto-detect on plain
    single-host machines can hard-abort the process, so without a coordinator
    and without cluster env vars this is a clean no-op.

    ``timeout_s`` runs the init under a watchdog: the round-5 outage showed
    it can HANG in native code with no error raised (OUTAGE_r5.json), and a
    hang must surface as ``WatchdogTimeout`` — raised for an explicit
    coordinator request, recorded in the failure log and degraded to
    single-host for auto-detection.

    .. note:: the watchdog can only *abandon* a hung native init thread, it
       cannot reclaim it (the thread leaks; ``watchdog.abandoned_total``
       counts them).  Callers that need the hang actually killed must
       pre-flight with the subprocess-isolated
       ``parallel.supervisor.probe_devices`` — a child process under
       SIGTERM→SIGKILL escalation is the only reclaim that works.

    Emits a ``multihost.init`` telemetry span around the attempt and sets
    the ``multihost.process_count`` / ``multihost.initialized`` gauges, so
    a degraded-to-single-host run is visible on dashboards and not just in
    the failure log.
    """
    from ..telemetry import REGISTRY, span
    already = getattr(jax.distributed, "is_initialized", None)
    if already is not None and already():
        REGISTRY.gauge("multihost.initialized").set(1)
        REGISTRY.gauge("multihost.process_count").set(jax.process_count())
        return jax.process_count() > 1
    if coordinator_address is None and not _cluster_env_present():
        return False
    try:
        with span("multihost.init",
                  coordinator=coordinator_address or "auto",
                  requested_processes=int(num_processes or 0),
                  timeout_s=float(timeout_s or 0)):
            maybe_inject("multihost.init", key=coordinator_address or "auto")
            run_with_deadline(
                jax.distributed.initialize, timeout_s,
                coordinator_address=coordinator_address,
                num_processes=num_processes, process_id=process_id,
                description="jax.distributed.initialize")
    except Exception as e:  # noqa: BLE001
        REGISTRY.gauge("multihost.initialized").set(0)
        if coordinator_address is not None:
            # an EXPLICIT multi-host request that fails must not silently
            # degrade to single-host (every host would train divergently)
            raise
        # auto-detected cluster env but init failed: degrade to single-host,
        # observably — exactly the demotion the round-5 probes did by hand
        record_failure("multihost.init_distributed", "degraded", e,
                       point="multihost.init", fallback="single-host")
        REGISTRY.gauge("multihost.process_count").set(1)
        return False
    REGISTRY.gauge("multihost.initialized").set(1)
    REGISTRY.gauge("multihost.process_count").set(jax.process_count())
    return jax.process_count() > 1


def is_multihost() -> bool:
    return jax.process_count() > 1
