"""One device data plane: ``DeviceTable`` over dense and COO payloads.

Dense rows got mesh sharding, bounded-chunk streaming, memory planning and
AOT zero-compile serving; sparse COO (the 100k-column hashed-text regime)
stayed single-device because the flat entry stream had no row-sharding
story.  ``DeviceTable`` is that story:

  * **row partitioning** — entries sort by row (stable, so same-row entry
    order is preserved) and partition exactly at the mesh's device row-shard
    boundaries via ``searchsorted``; ``row_ids`` stay GLOBAL, so every
    segment-sum consumer (``sp_matvec`` and friends) is already correct
    under GSPMD without per-shard rebasing;
  * **nnz ladder** — each device shard pads to one COMMON per-device entry
    capacity on the same {2^k, 1.5*2^k} ladder dense fit shapes use, so the
    assembled flat components divide evenly over the 'data' axis and the
    jitted programs specialize on a small set of capacities.  Pad entries
    are ``value 0.0`` — an exact zero addend for every segment sum;
  * **bounded streaming** — each shard's real entries ship in chunks under
    the same ``TRANSMOGRIFAI_DEVICE_CHUNK_BYTES`` budget as dense rows
    (the three flat components stage together, 12 B per entry), reusing the
    streaming module's double-buffer accounting so the ≤2×-chunk peak
    staging bound covers sparse too.  Pad entries synthesize on-device —
    zero host-link bytes;
  * **hostgroup addressing** — ``row_offset`` / ``global_rows`` position a
    local row slice in the global row space, mirroring
    ``stream_to_device``'s multi-process contract;
  * **memory planning / AOT stability** — ``nnz`` (ladder-rounded) is what
    ``plan_sweep_memory`` budgets for sparse payloads, and the sharded
    result is a plain :class:`SparseMatrix` (pytree-stable flat arrays), so
    the registry/AOT seams see the same leaf layout as the single-device
    path.

Counters (``device_table_stats`` / ``reset_device_table_stats``) surface as
read-through gauges ``device_table.*`` in ``telemetry.REGISTRY`` and ride
the bench ``aux.telemetry.mesh`` block next to the dense ``mesh.*`` family.
"""

from __future__ import annotations

import threading
from typing import Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from .mesh import data_axis_size, data_sharding

# one COO entry = f32 value + i32 col + i32 row = 12 host bytes; the three
# flat components stage together under one chunk budget
_ENTRY_BYTES = 12

_lock = threading.Lock()
_STATS = {
    "tables": 0,          # DeviceTable payloads shipped
    "rows": 0,            # logical rows shipped (padded row space)
    "nnz_streamed": 0,    # real COO entries moved over the host link
    "pad_entries": 0,     # ladder pad entries synthesized on-device
    "shards": 0,          # per-device shards assembled
}


def device_table_stats() -> dict:
    with _lock:
        return dict(_STATS)


def reset_device_table_stats() -> None:
    with _lock:
        for k in _STATS:
            _STATS[k] = 0


def _bump(**kv) -> None:
    with _lock:
        for k, v in kv.items():
            _STATS[k] += int(v)


class DeviceTable:
    """A host-side table (dense rows or COO entries) ready to ship to the
    data mesh.  ``kind`` is ``"dense"`` or ``"sparse"``; either way
    ``to_device(mesh, ...)`` returns the device-resident, row-sharded form
    (a ``jax.Array`` or a :class:`SparseMatrix`) with peak host staging
    bounded by ~2× the chunk budget."""

    __slots__ = ("kind", "payload", "n_rows", "n_cols", "row_offset",
                 "global_rows", "_coo")

    def __init__(self, kind: str, payload, n_rows: int, n_cols: int, *,
                 row_offset: int = 0, global_rows: Optional[int] = None,
                 coo: Optional[Tuple] = None):
        self.kind = kind
        self.payload = payload
        self.n_rows = int(n_rows)
        self.n_cols = int(n_cols)
        self.row_offset = int(row_offset)
        self.global_rows = int(global_rows) if global_rows is not None \
            else self.row_offset + self.n_rows
        self._coo = coo

    # ---- construction -------------------------------------------------
    @classmethod
    def from_dense(cls, arr, *, row_offset: int = 0,
                   global_rows: Optional[int] = None) -> "DeviceTable":
        host = np.asarray(arr)
        rows = host.shape[0]
        cols = host.shape[1] if host.ndim == 2 else 1
        return cls("dense", host, rows, cols, row_offset=row_offset,
                   global_rows=global_rows)

    @classmethod
    def from_sparse(cls, sm, *, row_offset: int = 0,
                    global_rows: Optional[int] = None) -> "DeviceTable":
        """From a :class:`SparseMatrix` (device or host components): pulls
        the REAL entries host-side and row-sorts them (stable — same-row
        entry order is preserved, so segment sums see the same addend order
        per row)."""
        r, c, v = sm.host_coo()
        order = np.argsort(r, kind="stable")
        coo = (np.asarray(r, np.int32)[order], np.asarray(c, np.int32)[order],
               np.asarray(v, np.float32)[order])
        return cls("sparse", sm, int(sm.n_rows), int(sm.n_cols),
                   row_offset=row_offset, global_rows=global_rows, coo=coo)

    @classmethod
    def from_coo(cls, rows, cols, vals, n_rows: int, n_cols: int, *,
                 row_offset: int = 0,
                 global_rows: Optional[int] = None) -> "DeviceTable":
        r = np.asarray(rows, np.int32)
        order = np.argsort(r, kind="stable")
        coo = (r[order], np.asarray(cols, np.int32)[order],
               np.asarray(vals, np.float32)[order])
        return cls("sparse", None, int(n_rows), int(n_cols),
                   row_offset=row_offset, global_rows=global_rows, coo=coo)

    # ---- shape / planning protocol ------------------------------------
    @property
    def is_sparse(self) -> bool:
        return self.kind == "sparse"

    @property
    def shape(self) -> Tuple[int, int]:
        return (self.n_rows, self.n_cols)

    @property
    def nnz(self) -> int:
        if self.is_sparse:
            return int(len(self._coo[0]))
        return int(self.n_rows * self.n_cols)

    @property
    def nbytes(self) -> int:
        """Host bytes the stream will move (real payload, before pads)."""
        if self.is_sparse:
            return self.nnz * _ENTRY_BYTES
        return int(np.asarray(self.payload).nbytes)

    def nnz_rung(self, extent: int = 1) -> int:
        """Ladder-rounded TOTAL entry capacity after sharding over
        ``extent`` devices — what the memory planner budgets."""
        from ..sparse.matrix import nnz_capacity
        if not self.is_sparse:
            return self.nnz
        extent = max(1, int(extent))
        if extent == 1:
            return nnz_capacity(self.nnz)
        per = -(-self.nnz // extent)
        return extent * nnz_capacity(per)

    # ---- device shipment ----------------------------------------------
    def to_device(self, mesh, *, pad_to: Optional[int] = None,
                  chunk_bytes: Optional[int] = None):
        """Ship this table to the mesh, row-sharded over 'data'.

        Dense tables delegate to :func:`stream_to_device` (row chunks);
        sparse tables stream nnz ranges per shard (see module docstring).
        ``pad_to`` grows the row space with zero-weight rows (dense) or
        empty rows (sparse) — both exact.
        """
        from .streaming import stream_to_device
        if not self.is_sparse:
            return stream_to_device(self.payload, mesh, pad_to=pad_to,
                                    chunk_bytes=chunk_bytes,
                                    row_offset=self.row_offset,
                                    global_rows=(self.global_rows
                                                 if self.global_rows
                                                 != self.row_offset
                                                 + self.n_rows else None))
        return _stream_sparse(self, mesh, pad_to=pad_to,
                              chunk_bytes=chunk_bytes)


def _stream_sparse(table: DeviceTable, mesh, *, pad_to: Optional[int],
                   chunk_bytes: Optional[int]):
    """Row-partition ``table``'s sorted COO entries at the mesh's device
    row-shard boundaries and assemble one data-sharded
    :class:`SparseMatrix` through bounded host chunks."""
    from ..sparse.matrix import SparseMatrix, nnz_capacity
    from ..telemetry import REGISTRY, event, span
    from .memory import effective_chunk_bytes
    from .streaming import (_STATS, _lock as _s_lock, _put_chunk, _stage,
                            _unstage, device_chunk_bytes)
    from ..profiling import add_host_link_bytes

    rows_g, cols_g, vals_g = table._coo
    rows_g = rows_g + np.int32(table.row_offset)
    n_rows = table.global_rows
    total_rows = n_rows if pad_to is None else max(int(pad_to), n_rows)
    extent = data_axis_size(mesh)
    if total_rows % extent:
        raise ValueError(
            f"sparse stream: padded row count {total_rows} is not "
            f"divisible by the data axis extent {extent}")
    rows_per = total_rows // extent

    # entry partition at the device row-shard boundaries: entries are
    # row-sorted, so each shard owns one contiguous entry range
    bounds = np.searchsorted(rows_g, np.arange(1, extent) * rows_per,
                             side="left")
    starts = np.concatenate([[0], bounds]).astype(np.int64)
    stops = np.concatenate([bounds, [len(rows_g)]]).astype(np.int64)
    counts = stops - starts
    # one COMMON per-device capacity on the nnz ladder: the flat components
    # then divide evenly over 'data' and the fit programs specialize on a
    # ladder rung instead of the exact entry count
    per_cap = nnz_capacity(int(counts.max()) if len(counts) else 0)
    total_cap = per_cap * extent

    budget = effective_chunk_bytes(
        chunk_bytes if chunk_bytes is not None else device_chunk_bytes())
    chunk_entries = max(1, budget // _ENTRY_BYTES)
    REGISTRY.gauge("mesh.chunk_bytes").set(budget)
    h2d = REGISTRY.counter("host_to_device_bytes_total")

    sharding = data_sharding(mesh, ndim=1)
    dev_map = sharding.addressable_devices_indices_map((total_cap,))
    # map each device to its entry-range index via its flat-component slice
    comp_shards = {0: [], 1: [], 2: []}   # values, indices, row_ids
    inflight = []
    with span("mesh.stream_to_device", rows=int(n_rows),
              pad_rows=int(total_rows - n_rows), sparse=True,
              nnz=int(len(rows_g)), per_device_capacity=int(per_cap),
              devices=len(dev_map), chunk_entries=int(chunk_entries)):
        for dev, idx in dev_map.items():
            (esl,) = idx
            d = (0 if esl.start is None else esl.start) // per_cap
            s, e = int(starts[d]), int(stops[d])
            pieces = {0: [], 1: [], 2: []}
            pos = s
            while pos < e:
                end = min(pos + chunk_entries, e)
                from .supervisor import next_chunk_key
                seq = next_chunk_key()
                nbytes = (end - pos) * _ENTRY_BYTES
                _stage(nbytes)
                with span("mesh.stream_chunk", device=str(dev),
                          entries=int(end - pos), bytes=int(nbytes),
                          seq=int(seq)):
                    try:
                        sent, bufs = [], []
                        for comp in (vals_g, cols_g, rows_g):
                            buf = np.ascontiguousarray(comp[pos:end])
                            bufs.append(buf)
                            sent.append(_put_chunk(buf, dev, seq))
                        for ci in range(3):
                            pieces[ci].append(sent[ci])
                    except BaseException:
                        _unstage(nbytes)
                        raise
                # double buffering: the chunk's three host buffers stay
                # alive while its transfers are in flight; before staging a
                # third chunk the oldest retires — peak staging ≤ 2 chunks
                inflight.append((sent, bufs, nbytes))
                if len(inflight) > 1:
                    old_sent, _old_bufs, old_bytes = inflight.pop(0)
                    for p in old_sent:
                        p.block_until_ready()
                    _unstage(old_bytes)
                h2d.inc(nbytes)
                add_host_link_bytes(nbytes)
                with _s_lock:
                    _STATS["chunks"] += 1
                    _STATS["bytes_streamed"] += nbytes
                pos = end
            pad = per_cap - (e - s)
            if pad:
                # pad entries synthesize on-device: value 0.0 (exact zero
                # addend) at this shard's first row / col 0 — in-range ids
                # keep every static-num_segments scatter well-formed
                pad_row = np.int32(min(d * rows_per, total_rows - 1))
                pieces[0].append(jax.device_put(
                    jnp.zeros((pad,), jnp.float32), dev))
                pieces[1].append(jax.device_put(
                    jnp.zeros((pad,), jnp.int32), dev))
                pieces[2].append(jax.device_put(
                    jnp.full((pad,), pad_row, jnp.int32), dev))
            for ci in range(3):
                comp_shards[ci].append(
                    pieces[ci][0] if len(pieces[ci]) == 1
                    else jnp.concatenate(pieces[ci]))
        while inflight:
            sent, _bufs, nbytes = inflight.pop(0)
            for p in sent:
                p.block_until_ready()
            _unstage(nbytes)
        comps = [jax.make_array_from_single_device_arrays(
                     (total_cap,), sharding, comp_shards[ci])
                 for ci in range(3)]
    with _s_lock:
        _STATS["arrays"] += 1
    _bump(tables=1, rows=total_rows, nnz_streamed=len(rows_g),
          pad_entries=total_cap - len(rows_g), shards=extent)
    if total_rows != n_rows:
        with _s_lock:
            _STATS["pad_rows"] += total_rows - n_rows
        event("mesh.stream_pad", rows=int(n_rows),
              pad_rows=int(total_rows - n_rows), sparse=True)
    return SparseMatrix(comps[0], comps[1], comps[2], total_rows,
                        table.n_cols, nnz=int(len(rows_g)))
