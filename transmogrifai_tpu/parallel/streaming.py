"""Chunked host→device streaming for mesh-sharded arrays.

The one-shot ``jax.device_put(X, data_sharding(mesh, 2))`` stages the whole
host matrix at once: at 11M × 1596 f32 that is a ~70GB transient on top of
the resident copy, which is exactly the cumulative-HBM/host-RSS pressure
that hard-faulted single workers (BENCH_11M_ATTEMPTS_r4).  This module
assembles each device's row shard from bounded host slices instead:

  * at most two chunk-sized host staging buffers are alive at any moment
    (double buffering: chunk *i* transfers while chunk *i+1* is sliced), so
    peak staging is O(TRANSMOGRIFAI_DEVICE_CHUNK_BYTES), not O(dataset);
  * pad rows (device-divisibility quantum, fit-shape ladder rungs) are
    synthesised on-device with ``jnp.zeros`` — zero host-link bytes;
  * the assembled shards are stitched into one logically-sharded array via
    ``jax.make_array_from_single_device_arrays``, indistinguishable to the
    compiled program from a one-shot ``device_put``.

Chunks are converted to f32 with the same elementwise ``astype`` the
one-shot path used, so the streamed array is bitwise-identical to
``jax.device_put(jnp.asarray(X, jnp.float32), sharding)`` on the real rows.
"""

from __future__ import annotations

import math
import os
import threading
from typing import Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from .mesh import data_sharding

_DEFAULT_CHUNK_BYTES = 256 * 1024 * 1024


def _put_chunk(buf, dev, seq: int):
    """One supervised chunk transfer.  A hung host→device link (the
    OUTAGE_r5 failure family) surfaces as a typed ``TransferStallError``
    within the TRANSMOGRIFAI_CHUNK_DEADLINE_S budget instead of blocking
    the stream forever; ``supervisor.chunk_stall`` is the chaos-injection
    point, keyed by a monotone per-process chunk sequence so a sticky
    fail_keys entry stalls one specific chunk and the sweep-recovery
    re-stream proceeds cleanly."""
    from ..resilience import (InjectedFault, WatchdogTimeout, maybe_inject,
                              run_with_deadline)
    from .supervisor import TransferStallError, chunk_deadline_s
    deadline = chunk_deadline_s()
    try:
        maybe_inject("supervisor.chunk_stall", key=seq)
        if deadline is None:
            return jax.device_put(buf, dev)
        return run_with_deadline(jax.device_put, deadline, buf, dev,
                                 description="mesh.stream_chunk")
    except (InjectedFault, WatchdogTimeout) as e:
        raise TransferStallError(
            f"host->device chunk {seq} to {dev} stalled: {e}") from e

_lock = threading.Lock()
_STATS = {
    "chunks": 0,
    "bytes_streamed": 0,
    "staging_bytes": 0,
    "peak_staging_bytes": 0,
    "pad_rows": 0,
    "arrays": 0,
}


def device_chunk_bytes() -> int:
    """Host-staging budget per transfer chunk
    (TRANSMOGRIFAI_DEVICE_CHUNK_BYTES, default 256MB)."""
    try:
        v = int(os.environ.get("TRANSMOGRIFAI_DEVICE_CHUNK_BYTES",
                               _DEFAULT_CHUNK_BYTES))
    except ValueError:
        return _DEFAULT_CHUNK_BYTES
    return max(1, v)


def streaming_stats() -> dict:
    with _lock:
        return dict(_STATS)


def reset_streaming_stats() -> None:
    with _lock:
        for k in _STATS:
            _STATS[k] = 0


def _stage(nbytes: int) -> None:
    with _lock:
        _STATS["staging_bytes"] += nbytes
        if _STATS["staging_bytes"] > _STATS["peak_staging_bytes"]:
            _STATS["peak_staging_bytes"] = _STATS["staging_bytes"]


def _unstage(nbytes: int) -> None:
    with _lock:
        _STATS["staging_bytes"] -= nbytes


def _row_slice(shape: Tuple[int, ...], row_axis: int,
               start: int, stop: int) -> Tuple[slice, ...]:
    idx = [slice(None)] * len(shape)
    idx[row_axis] = slice(start, stop)
    return tuple(idx)


def stream_to_device(arr,
                     mesh,
                     ndim: Optional[int] = None,
                     row_axis: int = 0,
                     chunk_bytes: Optional[int] = None,
                     pad_to: Optional[int] = None,
                     dtype=jnp.float32,
                     row_offset: int = 0,
                     global_rows: Optional[int] = None) -> jax.Array:
    """Build a data-sharded device array from ``arr`` through bounded host
    chunks, optionally padding ``row_axis`` up to ``pad_to`` with zero rows.

    Returns the same logical array as
    ``jax.device_put(jnp.asarray(arr_padded, dtype), data_sharding(...))``
    with peak host staging bounded by ~2×``chunk_bytes``.

    Multi-process (host group): ``arr`` may be just this rank's row shard —
    its reader slice — positioned in the global row space by ``row_offset``
    with ``global_rows`` the full logical row count (``mesh.process_row_range``
    computes the slice to materialize).  Each process ``device_put``s only
    its own addressable shards from its own slice; the shards assemble via
    ``make_array_from_single_device_arrays`` into the same global array,
    bitwise-equal to the single-process path on the real rows, with the
    staging bound unchanged.  A slice that does not cover this process's
    shard extent raises ``ValueError`` (typed, never silent misalignment).
    """
    from ..profiling import add_host_link_bytes
    from ..telemetry import REGISTRY, event, span

    # one device data plane (ISSUE 19): DeviceTable and SparseMatrix
    # payloads stream under the SAME chunk budget and staging bound —
    # dense tables chunk by rows, sparse tables by nnz ranges
    from .device_table import DeviceTable
    from ..sparse.matrix import SparseMatrix
    if isinstance(arr, SparseMatrix):
        arr = DeviceTable.from_sparse(arr, row_offset=row_offset,
                                      global_rows=global_rows)
    if isinstance(arr, DeviceTable):
        return arr.to_device(mesh, pad_to=pad_to, chunk_bytes=chunk_bytes)

    host = np.asarray(arr)
    if ndim is None:
        ndim = host.ndim
    n_local = host.shape[row_axis]
    row_offset = int(row_offset)
    n_rows = int(global_rows) if global_rows is not None \
        else row_offset + n_local
    if row_offset < 0 or row_offset + n_local > n_rows:
        raise ValueError(
            f"stream_to_device: local slice [{row_offset}, "
            f"{row_offset + n_local}) exceeds the global row space "
            f"[0, {n_rows})")
    total_rows = n_rows if pad_to is None else max(pad_to, n_rows)
    target_shape = list(host.shape)
    target_shape[row_axis] = total_rows
    target_shape = tuple(target_shape)

    sharding = data_sharding(mesh, ndim=ndim, row_axis=row_axis)
    np_dtype = np.dtype(dtype.dtype if hasattr(dtype, "dtype") else dtype)
    row_bytes = np_dtype.itemsize * max(
        1, int(np.prod([s for a, s in enumerate(target_shape)
                        if a != row_axis])))
    # the memory-governor degrade ladder halves the chunk budget per rung:
    # applies to explicit planner-chosen budgets too, so a post-OOM retry
    # streams smaller even when the caller pinned chunk_bytes
    from .memory import effective_chunk_bytes
    budget = effective_chunk_bytes(
        chunk_bytes if chunk_bytes is not None else device_chunk_bytes())
    chunk_rows = max(1, budget // row_bytes)

    REGISTRY.gauge("mesh.chunk_bytes").set(budget)
    h2d = REGISTRY.counter("host_to_device_bytes_total")

    # per-device shard extents under this sharding of the *padded* shape
    dev_map = sharding.addressable_devices_indices_map(target_shape)

    shards = []
    inflight = []  # (device_array, host_buffer, staged_bytes) double buffer
    with span("mesh.stream_to_device", rows=int(n_rows),
              local_rows=int(n_local), row_offset=int(row_offset),
              pad_rows=int(total_rows - n_rows),
              devices=len(dev_map), chunk_rows=int(chunk_rows)):
        for dev, idx in dev_map.items():
            rsl = idx[row_axis]
            start = 0 if rsl.start is None else rsl.start
            stop = total_rows if rsl.stop is None else rsl.stop
            real_stop = min(stop, n_rows)
            if start < real_stop and (start < row_offset
                                      or real_stop > row_offset + n_local):
                raise ValueError(
                    f"stream_to_device: this process's shard on {dev} "
                    f"needs global rows [{start}, {real_stop}) but the "
                    f"local slice only covers [{row_offset}, "
                    f"{row_offset + n_local}) — pass the slice from "
                    f"mesh.process_row_range")
            pieces = []
            pos = start
            while pos < real_stop:
                end = min(pos + chunk_rows, real_stop)
                view = host[_row_slice(host.shape, row_axis,
                                       pos - row_offset, end - row_offset)]
                buf = np.ascontiguousarray(view, dtype=np_dtype)
                nbytes = buf.nbytes
                _stage(nbytes)
                from .supervisor import next_chunk_key
                seq = next_chunk_key()
                with span("mesh.stream_chunk", device=str(dev),
                          rows=int(end - pos), bytes=int(nbytes),
                          seq=int(seq)):
                    try:
                        piece = _put_chunk(buf, dev, seq)
                    except BaseException:
                        _unstage(nbytes)
                        raise
                # double buffering: keep this chunk's host buffer alive while
                # its transfer is in flight, but before slicing a third chunk
                # retire the oldest one — at most two staging buffers exist.
                inflight.append((piece, buf, nbytes))
                if len(inflight) > 1:
                    old_piece, _old_buf, old_bytes = inflight.pop(0)
                    old_piece.block_until_ready()
                    _unstage(old_bytes)
                h2d.inc(nbytes)
                add_host_link_bytes(nbytes)
                with _lock:
                    _STATS["chunks"] += 1
                    _STATS["bytes_streamed"] += nbytes
                pieces.append(piece)
                pos = end
            if stop > real_stop:  # zero pad rows synthesised on-device
                pad_shape = list(target_shape)
                pad_shape[row_axis] = stop - max(real_stop, start)
                pieces.append(jax.device_put(
                    jnp.zeros(tuple(pad_shape), dtype=np_dtype), dev))
                with _lock:
                    _STATS["pad_rows"] += pad_shape[row_axis]
            if len(pieces) == 1:
                shard = pieces[0]
            else:
                shard = jnp.concatenate(pieces, axis=row_axis)
            shards.append(shard)
        while inflight:
            piece, _buf, nbytes = inflight.pop(0)
            piece.block_until_ready()
            _unstage(nbytes)
        out = jax.make_array_from_single_device_arrays(
            target_shape, sharding, shards)
    with _lock:
        _STATS["arrays"] += 1
    if total_rows != n_rows:
        event("mesh.stream_pad", rows=int(n_rows),
              pad_rows=int(total_rows - n_rows))
    return out
