"""Distributed execution on the TPU mesh — the re-expression of the
reference's parallelism mechanisms (SURVEY.md §2.6):

  P1 row data-parallelism (Spark RDD maps)      → batch sharding over 'data'
  P2 monoid stat reductions (Algebird)          → psum over ICI
  P3 (model × paramMap × fold) task parallelism → vmap over candidate axis,
                                                  sharded over 'model'
  P7 Spark shuffle/broadcast                    → XLA collectives via GSPMD
"""

from .device_table import (DeviceTable, device_table_stats,
                           reset_device_table_stats)
from .mesh import (candidate_mesh_for, candidate_sharding, data_axis_size,
                   data_sharding, make_mesh, maybe_data_mesh,
                   model_axis_size, model_axis_width, pad_rows_for,
                   process_row_range, replicated_sharding)
from .dist_fit import (fit_logreg_grid_sharded, sharded_col_stats,
                       sharded_forest_fit, sharded_gbt_round,
                       sharded_train_step)
from .hostgroup import (EXIT_HOST_LOST, HostGroup, HostGroupResult,
                        HostLiveness, HostLostError, barrier_sync,
                        hostgroup_env_present, launch_hosts,
                        maybe_init_hostgroup)
from .memory import (HostMemoryPressure, MemoryExhaustedError, MemoryPlan,
                     RssWatchdog, check_host_pressure, device_memory_budget,
                     is_memory_exhaustion, memory_governor_enabled,
                     plan_sweep_memory, reset_memory_degrade, shrink_level)
from .multihost import ensure_cpu_collectives, init_distributed, is_multihost
from .streaming import (device_chunk_bytes, stream_to_device,
                        streaming_stats)
from .supervisor import (DeviceLostError, Heartbeat, ProbeVerdict,
                         SupervisedResult, TransferStallError,
                         effective_device_count, is_device_loss,
                         mark_device_loss, probe_devices, probe_with_backoff,
                         reset_surviving_devices, run_supervised,
                         supervisor_enabled, write_outage_record)

__all__ = [
    "make_mesh", "maybe_data_mesh", "data_sharding", "candidate_sharding",
    "candidate_mesh_for", "replicated_sharding", "data_axis_size",
    "model_axis_size", "model_axis_width", "pad_rows_for",
    "process_row_range",
    "fit_logreg_grid_sharded", "sharded_col_stats", "sharded_forest_fit",
    "sharded_gbt_round", "sharded_train_step", "init_distributed",
    "is_multihost", "ensure_cpu_collectives",
    "EXIT_HOST_LOST", "HostGroup", "HostGroupResult", "HostLiveness",
    "HostLostError", "barrier_sync", "hostgroup_env_present",
    "launch_hosts", "maybe_init_hostgroup",
    "stream_to_device", "streaming_stats", "device_chunk_bytes",
    "DeviceTable", "device_table_stats", "reset_device_table_stats",
    "HostMemoryPressure", "MemoryExhaustedError", "MemoryPlan",
    "RssWatchdog", "check_host_pressure", "device_memory_budget",
    "is_memory_exhaustion", "memory_governor_enabled", "plan_sweep_memory",
    "reset_memory_degrade", "shrink_level",
    "DeviceLostError", "Heartbeat", "ProbeVerdict", "SupervisedResult",
    "TransferStallError", "effective_device_count", "is_device_loss",
    "mark_device_loss", "probe_devices", "probe_with_backoff",
    "reset_surviving_devices", "run_supervised", "supervisor_enabled",
    "write_outage_record",
]
