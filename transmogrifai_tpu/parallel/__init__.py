"""Distributed execution on the TPU mesh — the re-expression of the
reference's parallelism mechanisms (SURVEY.md §2.6):

  P1 row data-parallelism (Spark RDD maps)      → batch sharding over 'data'
  P2 monoid stat reductions (Algebird)          → psum over ICI
  P3 (model × paramMap × fold) task parallelism → vmap over candidate axis,
                                                  sharded over 'model'
  P7 Spark shuffle/broadcast                    → XLA collectives via GSPMD
"""

from .mesh import (candidate_sharding, data_sharding, make_mesh,
                   maybe_data_mesh, replicated_sharding)
from .dist_fit import (fit_logreg_grid_sharded, sharded_col_stats,
                       sharded_forest_fit, sharded_gbt_round,
                       sharded_train_step)
from .multihost import init_distributed, is_multihost

__all__ = [
    "make_mesh", "maybe_data_mesh", "data_sharding", "candidate_sharding",
    "replicated_sharding",
    "fit_logreg_grid_sharded", "sharded_col_stats", "sharded_forest_fit",
    "sharded_gbt_round", "sharded_train_step", "init_distributed",
    "is_multihost",
]
