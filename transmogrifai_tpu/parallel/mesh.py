"""Device-mesh construction and sharding helpers.

The mesh has two logical axes:
  * ``data``  — rows of the feature matrix (SURVEY §2.6 P1); stat reductions
    become psum/reduce-scatter over ICI (P2);
  * ``model`` — CV-grid candidates (fold × hyper-parameter), the TPU
    re-expression of the reference's thread-pool fit fan-out
    (OpValidator.scala:320-349, P3).

Multi-host: `jax.distributed` initialises the runtime; `jax.devices()` then
spans hosts and the same mesh code rides DCN across slices.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"


def make_mesh(n_devices: Optional[int] = None,
              model_parallel: int = 1,
              axis_names: Tuple[str, str] = (DATA_AXIS, MODEL_AXIS)) -> Mesh:
    """Build a (data × model) mesh over the first ``n_devices`` devices.

    ``model_parallel`` devices are assigned to the candidate axis; the rest to
    the data axis.  With a single device both axes have extent 1 and every
    sharding degenerates to fully-replicated — the same program runs anywhere.
    """
    devs = jax.devices()
    n = len(devs) if n_devices is None else min(n_devices, len(devs))
    devs = devs[:n]
    if n % model_parallel != 0:
        raise ValueError(f"n_devices {n} not divisible by model_parallel "
                         f"{model_parallel}")
    arr = np.array(devs).reshape(n // model_parallel, model_parallel)
    return Mesh(arr, axis_names)


def maybe_data_mesh(n_rows: int) -> Optional[Mesh]:
    """The process-wide data-axis mesh policy, shared by every stage that
    row-shards (validator CV grid, SanityChecker stats, RawFeatureFilter
    reductions, the compiled score program): a mesh when several devices are
    visible and the batch is big enough to shard profitably.  Force on/off
    with TRANSMOGRIFAI_TPU_MESH=1/0; row threshold via
    TRANSMOGRIFAI_TPU_MESH_MIN_ROWS.  Returns None when sharding would not
    apply (single device, small batch, or rows not divisible — static shapes
    stay exact, no padding surprises)."""
    import os

    n_dev = len(jax.devices())
    flag = os.environ.get("TRANSMOGRIFAI_TPU_MESH")
    if flag == "0" or n_dev < 2:
        return None
    min_rows = int(os.environ.get("TRANSMOGRIFAI_TPU_MESH_MIN_ROWS", 262144))
    if flag != "1" and n_rows < min_rows:
        return None
    if n_rows % n_dev:
        return None
    # resolve through the package attribute (not this module's global) so
    # callers/tests that instrument `parallel.make_mesh` see every mesh
    # construction
    from transmogrifai_tpu import parallel as _pkg
    return _pkg.make_mesh()


def data_sharding(mesh: Mesh, ndim: int = 2, row_axis: int = 0) -> NamedSharding:
    """Shard ``row_axis`` (default axis 0, rows) over 'data', replicate the
    rest — e.g. ``row_axis=1`` for [folds, rows] weight masks."""
    spec = [None] * ndim
    spec[row_axis] = DATA_AXIS
    return NamedSharding(mesh, P(*spec))


def candidate_sharding(mesh: Mesh, ndim: int = 1) -> NamedSharding:
    """Shard axis 0 (grid candidates) over 'model'."""
    spec = P(MODEL_AXIS, *([None] * (ndim - 1)))
    return NamedSharding(mesh, spec)


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
