"""Device-mesh construction and sharding helpers.

The mesh has two logical axes:
  * ``data``  — rows of the feature matrix (SURVEY §2.6 P1); stat reductions
    become psum/reduce-scatter over ICI (P2);
  * ``model`` — CV-grid candidates (fold × hyper-parameter), the TPU
    re-expression of the reference's thread-pool fit fan-out
    (OpValidator.scala:320-349, P3).

Multi-host: `jax.distributed` initialises the runtime; `jax.devices()` then
spans hosts and the same mesh code rides DCN across slices.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence, Tuple

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"


def make_mesh(n_devices: Optional[int] = None,
              model_parallel: int = 1,
              axis_names: Tuple[str, str] = (DATA_AXIS, MODEL_AXIS)) -> Mesh:
    """Build a (data × model) mesh over the first ``n_devices`` devices.

    ``model_parallel`` devices are assigned to the candidate axis; the rest to
    the data axis.  With a single device both axes have extent 1 and every
    sharding degenerates to fully-replicated — the same program runs anywhere.
    """
    devs = jax.devices()
    n = len(devs) if n_devices is None else min(n_devices, len(devs))
    devs = devs[:n]
    if n % model_parallel != 0:
        raise ValueError(f"n_devices {n} not divisible by model_parallel "
                         f"{model_parallel}")
    arr = np.array(devs).reshape(n // model_parallel, model_parallel)
    return Mesh(arr, axis_names)


def model_axis_width() -> int:
    """Requested 'model'-axis extent (TRANSMOGRIFAI_TPU_MESH_MODEL, default
    1 = grid candidates replicated).  Silently clamps to 1 when the device
    count is not divisible by the requested width."""
    try:
        w = int(os.environ.get("TRANSMOGRIFAI_TPU_MESH_MODEL", "1"))
    except ValueError:
        return 1
    if w < 1 or len(jax.devices()) % w:
        return 1
    # memory degrade ladder rung 3: give the model axis's devices back to
    # the data axis so each candidate lane spans more aggregate HBM
    from .memory import model_axis_collapsed
    if model_axis_collapsed():
        return 1
    return w


def maybe_data_mesh(n_rows: int, pad: bool = False) -> Optional[Mesh]:
    """The process-wide data-axis mesh policy, shared by every stage that
    row-shards (validator CV grid, SanityChecker stats, RawFeatureFilter
    reductions, the compiled score program): a mesh when several devices are
    visible and the batch is big enough to shard profitably.  Force on/off
    with TRANSMOGRIFAI_TPU_MESH=1/0; row threshold via
    TRANSMOGRIFAI_TPU_MESH_MIN_ROWS; 'model'-axis width via
    TRANSMOGRIFAI_TPU_MESH_MODEL.

    ``pad=False`` (stat reductions, score programs — callers that device_put
    the batch as-is) keeps the historical bail on ``n_rows`` not divisible by
    the data-axis extent: static shapes stay exact, no padding surprises.
    ``pad=True`` (the validator sweep, which pads with zero-weight rows)
    returns the mesh anyway and records a ``mesh.pad_rows`` telemetry event so
    the padding is visible in traces instead of silently degrading to one
    device.

    After a mid-run device loss the supervisor caps the usable device count
    (``supervisor.mark_device_loss``), so every mesh built here — including
    the sweep-recovery rebuild — spans only the surviving devices.  Explicit
    ``make_mesh(n)`` calls stay unclamped."""
    n_dev = len(jax.devices())
    from .supervisor import effective_device_count
    n_dev = effective_device_count(n_dev)
    flag = os.environ.get("TRANSMOGRIFAI_TPU_MESH")
    if flag == "0" or n_dev < 2:
        return None
    min_rows = int(os.environ.get("TRANSMOGRIFAI_TPU_MESH_MIN_ROWS", 262144))
    if flag != "1" and n_rows < min_rows:
        return None
    model = model_axis_width()
    if n_dev % model:
        # surviving-device count may not divide the requested model width
        # (8 devices / width 2 → 7 survivors): collapse the model axis
        # rather than refuse to build the recovery mesh
        model = 1
    data_extent = n_dev // model
    rem = n_rows % data_extent
    if rem:
        if not pad:
            return None
        from ..telemetry import event
        event("mesh.pad_rows", rows=n_rows, pad_rows=data_extent - rem,
              data_extent=data_extent, devices=n_dev)
    # resolve through the package attribute (not this module's global) so
    # callers/tests that instrument `parallel.make_mesh` see every mesh
    # construction
    from transmogrifai_tpu import parallel as _pkg
    mesh = _pkg.make_mesh(n_dev, model_parallel=model)
    from ..telemetry import REGISTRY
    REGISTRY.gauge("mesh.devices").set(n_dev)
    return mesh


def data_axis_size(mesh: Mesh) -> int:
    return mesh.shape[DATA_AXIS]


def model_axis_size(mesh: Mesh) -> int:
    return mesh.shape[MODEL_AXIS]


def pad_rows_for(n_rows: int, mesh: Mesh) -> int:
    """Zero-weight rows needed to make ``n_rows`` divisible by the data-axis
    extent (0 when already divisible)."""
    extent = data_axis_size(mesh)
    return (-n_rows) % extent


def data_sharding(mesh: Mesh, ndim: int = 2, row_axis: int = 0) -> NamedSharding:
    """Shard ``row_axis`` (default axis 0, rows) over 'data', replicate the
    rest — e.g. ``row_axis=1`` for [folds, rows] weight masks."""
    spec = [None] * ndim
    spec[row_axis] = DATA_AXIS
    return NamedSharding(mesh, P(*spec))


def process_row_range(mesh: Mesh, n_rows: int, ndim: int = 2,
                      row_axis: int = 0,
                      pad_to: Optional[int] = None) -> "tuple[int, int]":
    """Global row extent ``[lo, hi)`` covered by THIS process's addressable
    devices under ``data_sharding(mesh)`` — i.e. the slice of the global
    row space this host must materialize from its reader (its per-host
    shard).  Single-process meshes cover everything: ``(0, n_rows)``.
    ``pad_to`` must match the ``stream_to_device`` call so the shard
    boundaries of the padded shape are used; the returned extent is still
    clipped to the ``n_rows`` real rows (pad rows are synthesized
    on-device, never read)."""
    total = n_rows if pad_to is None else max(pad_to, n_rows)
    shape = [1] * ndim
    shape[row_axis] = total
    sh = data_sharding(mesh, ndim=ndim, row_axis=row_axis)
    dev_map = sh.addressable_devices_indices_map(tuple(shape))
    lo, hi = total, 0
    for idx in dev_map.values():
        rsl = idx[row_axis]
        lo = min(lo, 0 if rsl.start is None else rsl.start)
        hi = max(hi, total if rsl.stop is None else rsl.stop)
    return min(int(lo), int(n_rows)), min(int(hi), int(n_rows))


def candidate_sharding(mesh: Mesh, ndim: int = 1) -> NamedSharding:
    """Shard axis 0 (grid candidates) over 'model'."""
    spec = P(MODEL_AXIS, *([None] * (ndim - 1)))
    return NamedSharding(mesh, spec)


def candidate_mesh_for(X, n_candidates: int) -> Optional[Mesh]:
    """The mesh riding on ``X``'s sharding, when its 'model' axis can shard
    ``n_candidates`` grid points evenly (extent > 1, count divisible) — the
    fitters use this to lay hyper-parameter vectors out over 'model' via
    ``candidate_sharding`` instead of replicating them, without threading a
    mesh argument through every fit signature."""
    sh = getattr(X, "sharding", None)
    mesh = getattr(sh, "mesh", None)
    if mesh is None or not hasattr(mesh, "shape"):
        return None
    try:
        width = dict(mesh.shape).get(MODEL_AXIS, 1)
    except Exception:  # noqa: BLE001 — exotic sharding: replicate
        return None
    if width < 2 or n_candidates % width:
        return None
    if hasattr(mesh, "devices"):
        return mesh
    return None


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
