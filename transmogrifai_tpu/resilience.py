"""Resilience — policy-driven failure handling for the execution layer.

The reference system survives messy *data* (SanityChecker, RawFeatureFilter);
this module makes the *execution* layer survive messy infrastructure.  The
round-4/5 TPU-tunnel outage (OUTAGE_r5.json) showed device init hanging in
native code with no error raised, and before this module a single failing
grid candidate, poisoned micro-batch, or flaky device dispatch aborted an
entire ``train()`` or streaming-score run while ~20 ad-hoc silent ``except
Exception`` blocks hid the rest.  Four pieces replace that:

* ``RetryPolicy`` — exponential backoff with deterministic jitter and an
  optional per-attempt deadline; ``policy.call(fn)`` retries transient
  failures and records every retry in the active ``FailureLog``.
* ``run_with_deadline`` — a watchdog that runs a risky (device-touching)
  call in a worker thread and raises ``WatchdogTimeout`` when it does not
  return in time, so a native hang cannot stall the host loop (the probe
  discipline OUTAGE_r5.json's mitigations used, as a library primitive).
* ``FailureLog`` — every swallowed / retried / degraded / dead-lettered
  event is recorded with the stage uid, injection-point name and cause.
  ``Workflow.train`` exposes the log on the returned model; the streaming
  runner exposes it on the run result.  The ambient log (``use_failure_log``)
  lets deep code (compiled-program demotions, device-dispatch fallbacks,
  multihost init) report without threading a handle through every call.
* ``FaultInjector`` — a chaos-test harness with named injection points
  (``selector.candidate_fit``, ``streaming.batch``, ...).  Decisions are a
  pure function of (seed, point, key), so a given seed reproduces the exact
  same failure set — and therefore the exact same failure log — on every run.
"""

from __future__ import annotations

import hashlib
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import (Any, Callable, Dict, Iterable, List, Optional, Sequence,
                    Tuple)


# --------------------------------------------------------------------------
# errors
# --------------------------------------------------------------------------

class InjectedFault(RuntimeError):
    """Raised by FaultInjector at an armed injection point."""


class WatchdogTimeout(TimeoutError):
    """A watchdogged call did not return before its deadline.

    The worker thread is abandoned (daemonized): native hangs — the failure
    mode of the round-5 tunnel outage — cannot be interrupted from Python,
    so the only safe recovery is to stop waiting and degrade."""


class AllCandidatesFailed(RuntimeError):
    """Every (model × grid-point) candidate of a selector sweep failed.

    Carries the per-candidate causes so the aggregate error is actionable
    instead of a bare "nothing survived"."""

    def __init__(self, message: str, causes: Optional[Dict[str, str]] = None):
        self.causes = dict(causes or {})
        if self.causes:
            detail = "; ".join(f"{k}: {v}" for k, v in
                               sorted(self.causes.items()))
            message = f"{message} — per-candidate causes: {detail}"
        super().__init__(message)


# --------------------------------------------------------------------------
# failure log
# --------------------------------------------------------------------------

def _format_cause(cause: Any) -> str:
    if cause is None:
        return ""
    if isinstance(cause, BaseException):
        return f"{type(cause).__name__}: {cause}"
    return str(cause)


@dataclass
class FailureEvent:
    """One swallowed / retried / degraded execution event."""

    seq: int
    stage: str              # stage uid / model name / subsystem
    action: str             # see FailureLog.ACTIONS
    cause: str              # "ExcType: message" (or free text)
    point: str = ""         # injection-point / site name, e.g. "streaming.batch"
    attempt: int = 0        # retry attempt number (0 = not a retry)
    detail: Dict[str, Any] = field(default_factory=dict)
    time_s: float = 0.0     # wall clock; excluded from signature()

    def to_json(self) -> Dict[str, Any]:
        d = {"seq": self.seq, "stage": self.stage, "action": self.action,
             "cause": self.cause, "point": self.point,
             "attempt": self.attempt, "time": self.time_s}
        if self.detail:
            d["detail"] = dict(self.detail)
        return d


class FailureLog:
    """Append-only, thread-safe record of degradation events.

    Worker threads (the validator's candidate pool, watchdog workers) record
    into the same log the orchestrating call installed, so a train run's log
    is complete even though fits fan out."""

    ACTIONS = ("retried",      # transient failure, will try again
               "skipped",      # unit of work abandoned, sweep continues
               "dead_letter",  # exhausted retries, routed to the DLQ
               "demoted",      # stage moved off the compiled/device path
               "degraded",     # optimization abandoned, slower path taken
               "fallback",     # alternate implementation used
               "swallowed",    # best-effort side work failed silently before
               "resumed",      # unit of work replayed from a checkpoint
               "preempted",    # graceful stop requested mid-run
               "reloaded",     # serving swapped in a newer model version
               "promoted",     # lifecycle candidate won the holdout gate
               "rejected",     # lifecycle candidate lost; incumbent kept
               "shed",         # admission control rejected work up front
               "quarantined",  # data-quality firewall excluded a record/row
               "evicted",      # size-capped store dropped an entry (GC)
               "breaker_open",       # circuit breaker tripped: calls skipped
               "breaker_half_open",  # breaker probing for recovery
               "breaker_closed",     # breaker recovered: calls flow again
               "outage",       # device runtime declared down (supervisor)
               "recovered",    # device runtime back after outage/degrade
               "host_lost",      # host-group rank dead / heartbeat silent
               "host_recovered",  # host-group rank heartbeat resumed
               "relaunched",   # host group rebooted at shrunken world size
               "escalated",    # SIGTERM ignored; SIGKILL reclaimed it
               "tenant.activated",    # multi-tenant: bundle loaded on demand
               "tenant.evicted",      # multi-tenant: LRU/budget unload
               "tenant.quarantined",  # multi-tenant: bundle parked as toxic
               "tenant.reactivated",  # multi-tenant: quarantine probe passed
               "tenant.removed")      # multi-tenant: bundle dir disappeared

    def __init__(self):
        self._events: List[FailureEvent] = []
        self._lock = threading.Lock()

    def record(self, stage: str, action: str, cause: Any = None, *,
               point: str = "", attempt: int = 0, **detail) -> FailureEvent:
        if action not in self.ACTIONS:
            raise ValueError(f"unknown failure action {action!r}; "
                             f"expected one of {self.ACTIONS}")
        if "span_id" not in detail:
            # correlate with the ambient trace: the span this failure was
            # recorded inside.  Safe for chaos determinism — signature()
            # excludes detail.  Late import: telemetry imports profiling only.
            from .telemetry import current_span_id
            sid = current_span_id()
            if sid is not None:
                detail["span_id"] = sid
        with self._lock:
            ev = FailureEvent(seq=len(self._events), stage=str(stage),
                              action=action, cause=_format_cause(cause),
                              point=point, attempt=int(attempt),
                              detail=dict(detail), time_s=time.time())
            self._events.append(ev)
            return ev

    @property
    def events(self) -> List[FailureEvent]:
        with self._lock:
            return list(self._events)

    def by_action(self, action: str) -> List[FailureEvent]:
        return [e for e in self.events if e.action == action]

    def by_stage(self, stage: str) -> List[FailureEvent]:
        return [e for e in self.events if e.stage == stage]

    def summary(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for e in self.events:
            out[e.action] = out.get(e.action, 0) + 1
        return out

    def signature(self) -> List[Tuple[str, str, str, str, int]]:
        """The deterministic projection of the log: everything except wall
        time and seq.  Two runs with the same seed/injector must produce
        equal signatures (the acceptance contract for chaos tests).  Sorted
        so thread-pool completion order cannot reorder it."""
        return sorted((e.stage, e.point, e.action, e.cause, e.attempt)
                      for e in self.events)

    def to_json(self) -> List[Dict[str, Any]]:
        return [e.to_json() for e in self.events]

    def extend(self, other: "FailureLog") -> None:
        for e in other.events:
            self.record(e.stage, e.action, e.cause, point=e.point,
                        attempt=e.attempt, **e.detail)

    def clear(self) -> None:
        with self._lock:
            self._events.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)

    def __iter__(self):
        return iter(self.events)

    def __repr__(self) -> str:
        return f"FailureLog({self.summary() or 'empty'})"


# Ambient log: a process-global stack (NOT thread-local — the validator's
# candidate fits run on a thread pool and must report into the log their
# orchestrating train() installed).  Concurrent *independent* runs in one
# process should pass explicit logs instead.
_LOG_STACK: List[FailureLog] = []
_LOG_LOCK = threading.Lock()
DEFAULT_LOG = FailureLog()


def active_failure_log() -> FailureLog:
    """The innermost installed log, or the process-default catch-all."""
    with _LOG_LOCK:
        return _LOG_STACK[-1] if _LOG_STACK else DEFAULT_LOG


@contextmanager
def use_failure_log(log: FailureLog):
    """Install ``log`` as the ambient failure log for the dynamic extent."""
    with _LOG_LOCK:
        _LOG_STACK.append(log)
    try:
        yield log
    finally:
        with _LOG_LOCK:
            # remove the last occurrence (robust to interleaved exits)
            for i in range(len(_LOG_STACK) - 1, -1, -1):
                if _LOG_STACK[i] is log:
                    del _LOG_STACK[i]
                    break


def record_failure(stage: str, action: str, cause: Any = None, *,
                   point: str = "", attempt: int = 0, **detail) -> FailureEvent:
    """Record into the ambient log — the one-liner deep code uses."""
    return active_failure_log().record(stage, action, cause, point=point,
                                       attempt=attempt, **detail)


# --------------------------------------------------------------------------
# deterministic hashing (shared by jitter and fault decisions)
# --------------------------------------------------------------------------

def _stable_uniform(*parts: Any) -> float:
    """Uniform [0, 1) as a pure function of the parts — independent of
    PYTHONHASHSEED, process, platform and call order."""
    h = hashlib.sha256("|".join(str(p) for p in parts).encode()).digest()
    return int.from_bytes(h[:8], "big") / float(1 << 64)


# --------------------------------------------------------------------------
# watchdog
# --------------------------------------------------------------------------

def run_with_deadline(fn: Callable[..., Any], timeout_s: Optional[float],
                      *args, description: str = "", **kwargs) -> Any:
    """Run ``fn`` with a deadline; raise ``WatchdogTimeout`` if it blows it.

    The call runs in a daemon worker thread and the caller waits at most
    ``timeout_s``.  A call that never returns (a native hang in device init
    or dispatch — OUTAGE_r5.json's failure mode) is *abandoned*, not
    interrupted: Python cannot cancel native code, so the worker leaks by
    design and the host loop stays alive.  An abandoned worker that later
    completes drops its result/exception instead of pinning it in memory,
    and records the orphaned completion into the FailureLog that was ambient
    at call time.  Worker exceptions re-raise in the caller with the
    worker's own traceback attached.  ``timeout_s=None`` runs inline."""
    if timeout_s is None:
        return fn(*args, **kwargs)
    box: Dict[str, Any] = {}
    done = threading.Event()
    state_lock = threading.Lock()
    abandoned = False
    # captured NOW: by the time an abandoned worker finishes, the caller's
    # use_failure_log() context may have exited
    log = active_failure_log()
    label = description or getattr(fn, "__name__", "call")

    def target():
        err: Optional[BaseException] = None
        try:
            value = fn(*args, **kwargs)
        except BaseException as e:  # noqa: BLE001 — re-raised in the caller
            err, value = e, None
        with state_lock:
            orphaned = abandoned
            if not orphaned:
                if err is None:
                    box["value"] = value
                else:
                    box["error"] = err
        done.set()
        if orphaned:
            # the caller gave up long ago: do NOT keep the (possibly large)
            # result alive; leave an audit trail instead
            try:
                log.record("watchdog", "swallowed",
                           err if err is not None else
                           "worker completed after its deadline; "
                           "result dropped",
                           point="watchdog.orphan", description=label)
            except Exception:  # noqa: BLE001 — never crash an orphan thread
                pass

    worker = threading.Thread(target=target, daemon=True,
                              name=f"watchdog:{label}")
    worker.start()
    if not done.wait(timeout_s):
        with state_lock:
            # re-check under the lock: the worker may have delivered between
            # the wait timing out and us abandoning it
            if "value" not in box and "error" not in box:
                abandoned = True
        if abandoned:
            # zombie-thread accumulation is an OUTAGE_r5 symptom: make every
            # abandonment observable (counter + failure-log note) instead of
            # silent.  Only the subprocess supervisor can actually RECLAIM a
            # native hang — this records that we could not.
            try:
                from .telemetry import REGISTRY
                REGISTRY.counter("watchdog.abandoned_total").inc()
            except Exception:  # noqa: BLE001 — never mask the timeout
                pass
            try:
                log.record("watchdog", "degraded",
                           f"{label} worker thread abandoned after "
                           f"{timeout_s:g}s (native hang; thread leaked)",
                           point="watchdog.abandoned", description=label)
            except Exception:  # noqa: BLE001
                pass
            raise WatchdogTimeout(
                f"{label} exceeded its "
                f"{timeout_s:g}s deadline; worker thread abandoned (native "
                "hangs cannot be interrupted from Python — see "
                "OUTAGE_r5.json)")
    if "error" in box:
        err = box["error"]
        raise err.with_traceback(err.__traceback__)
    return box.get("value")


# --------------------------------------------------------------------------
# retry policy
# --------------------------------------------------------------------------

@dataclass
class RetryPolicy:
    """Exponential backoff with deterministic jitter and optional deadline.

    ``call(fn)`` runs ``fn`` up to ``max_attempts`` times.  Each attempt may
    additionally be watchdogged (``timeout_s``), so a hanging attempt counts
    as a failed attempt instead of stalling the loop forever.  Every retry is
    recorded in the supplied (or ambient) ``FailureLog``; the final failure
    propagates to the caller, which decides skip / dead-letter / raise."""

    max_attempts: int = 3
    base_delay_s: float = 0.05
    max_delay_s: float = 2.0
    multiplier: float = 2.0
    jitter: float = 0.25            # ± fraction of the nominal delay
    timeout_s: Optional[float] = None    # per-attempt watchdog deadline
    retry_on: Tuple[type, ...] = (Exception,)
    seed: int = 0                   # jitter determinism

    def delay_for(self, attempt: int, key: Any = "") -> float:
        """Backoff before retry #``attempt`` (1-based), deterministic in
        (seed, key, attempt)."""
        nominal = min(self.base_delay_s * self.multiplier ** (attempt - 1),
                      self.max_delay_s)
        if self.jitter <= 0:
            return nominal
        u = _stable_uniform(self.seed, "retry-jitter", key, attempt)
        return nominal * (1.0 + self.jitter * (2.0 * u - 1.0))

    def call(self, fn: Callable[[], Any], *, stage: str = "",
             point: str = "", key: Any = "", log: Optional[FailureLog] = None,
             sleep: Callable[[float], None] = time.sleep,
             description: str = "") -> Any:
        # `is None`, not truthiness — an empty FailureLog is falsy via __len__
        log = active_failure_log() if log is None else log
        last: Optional[BaseException] = None
        for attempt in range(1, max(1, self.max_attempts) + 1):
            try:
                return run_with_deadline(fn, self.timeout_s,
                                         description=description or point)
            except self.retry_on as e:  # noqa: PERF203
                last = e
                if attempt >= self.max_attempts:
                    raise
                log.record(stage or point or "retry", "retried", e,
                           point=point, attempt=attempt, key=str(key))
                sleep(self.delay_for(attempt, key=key))
        raise last  # pragma: no cover — loop always returns or raises


# --------------------------------------------------------------------------
# circuit breaker
# --------------------------------------------------------------------------

class CircuitOpenError(RuntimeError):
    """The breaker is open: the protected call was skipped outright.

    Carries ``retry_after_s`` — how long until the breaker will grant a
    recovery probe — so admission layers can surface an honest
    ``Retry-After`` instead of a guess."""

    def __init__(self, message: str, retry_after_s: float = 0.0):
        super().__init__(message)
        self.retry_after_s = float(retry_after_s)


class CircuitBreaker:
    """Thread-safe closed → open → half-open breaker with deterministic
    recovery probes.

    * **closed** — outcomes feed a sliding window.  The breaker opens on
      ``failure_threshold`` consecutive failures, or when the window holds
      at least ``min_calls`` outcomes and the failure fraction reaches
      ``failure_rate``.
    * **open** — ``allow()`` refuses every call until ``reset_timeout_s``
      has elapsed (``retry_after_s()`` says how long is left).
    * **half-open** — after the reset timeout, exactly ``half_open_probes``
      calls are granted as recovery probes (deterministic: a fixed permit
      count, no randomness).  If every probe succeeds the breaker closes
      and the window clears; any probe failure re-opens it for another
      full ``reset_timeout_s``.

    Transitions are recorded into the ambient ``FailureLog``
    (``breaker_open`` / ``breaker_half_open`` / ``breaker_closed``), as
    telemetry events (``breaker.transition``), and — when a registry is
    supplied — as per-breaker counters plus a state gauge
    (0 closed / 1 half-open / 2 open)."""

    CLOSED = "closed"
    OPEN = "open"
    HALF_OPEN = "half_open"
    _STATE_CODES = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}

    def __init__(self, name: str, *, window: int = 20,
                 failure_threshold: int = 5, failure_rate: float = 0.5,
                 min_calls: int = 10, reset_timeout_s: float = 30.0,
                 half_open_probes: int = 1,
                 clock: Callable[[], float] = time.monotonic,
                 registry: Optional[Any] = None):
        self.name = str(name)
        self.window = max(1, int(window))
        self.failure_threshold = max(1, int(failure_threshold))
        self.failure_rate = float(failure_rate)
        self.min_calls = max(1, int(min_calls))
        self.reset_timeout_s = float(reset_timeout_s)
        self.half_open_probes = max(1, int(half_open_probes))
        self._clock = clock
        self._lock = threading.Lock()
        self._state = self.CLOSED
        self._outcomes: List[bool] = []   # sliding window, True = failure
        self._consecutive_failures = 0
        self._opened_at = 0.0
        self._probe_permits = 0
        self._probe_successes = 0
        self._last_cause = ""
        self._registry = registry
        if registry is not None:
            registry.gauge(f"breaker.{self.name}.state", self.state_code)

    # -- state inspection --------------------------------------------------
    def state_code(self) -> int:
        return self._STATE_CODES[self.current_state()]

    def current_state(self) -> str:
        """The externally-visible state.  An open breaker whose reset
        timeout has elapsed reads as half-open (the next ``allow()`` will
        grant a probe) without mutating anything."""
        with self._lock:
            if (self._state == self.OPEN
                    and self._clock() - self._opened_at
                    >= self.reset_timeout_s):
                return self.HALF_OPEN
            return self._state

    def retry_after_s(self) -> float:
        """Seconds until the breaker will grant a recovery probe (0 when
        not open)."""
        with self._lock:
            if self._state != self.OPEN:
                return 0.0
            return max(0.0, self._opened_at + self.reset_timeout_s
                       - self._clock())

    def snapshot(self) -> Dict[str, Any]:
        state = self.current_state()
        with self._lock:
            failures = sum(self._outcomes)
            return {"name": self.name, "state": state,
                    "window_calls": len(self._outcomes),
                    "window_failures": failures,
                    "consecutive_failures": self._consecutive_failures,
                    "last_cause": self._last_cause,
                    "retry_after_s": (max(
                        0.0, self._opened_at + self.reset_timeout_s
                        - self._clock())
                        if self._state == self.OPEN else 0.0)}

    # -- the protocol ------------------------------------------------------
    def allow(self) -> bool:
        """May this call proceed?  Open→half-open happens here (lazily, on
        the first call after the reset timeout)."""
        with self._lock:
            if self._state == self.CLOSED:
                return True
            if self._state == self.OPEN:
                if (self._clock() - self._opened_at
                        < self.reset_timeout_s):
                    return False
                self._transition(self.HALF_OPEN,
                                 f"reset timeout {self.reset_timeout_s:g}s "
                                 "elapsed")
                self._probe_permits = self.half_open_probes
                self._probe_successes = 0
            # half-open: grant the remaining probe permits, refuse the rest
            if self._probe_permits > 0:
                self._probe_permits -= 1
                return True
            return False

    def record_success(self) -> None:
        with self._lock:
            self._consecutive_failures = 0
            if self._state == self.HALF_OPEN:
                self._probe_successes += 1
                if self._probe_successes >= self.half_open_probes:
                    self._transition(
                        self.CLOSED,
                        f"{self._probe_successes} recovery probe(s) "
                        "succeeded")
                    self._outcomes.clear()
                    self._last_cause = ""
                return
            if self._state == self.CLOSED:
                self._push_outcome(False)

    def record_failure(self, cause: Any = None) -> None:
        with self._lock:
            self._last_cause = _format_cause(cause)
            if self._state == self.HALF_OPEN:
                self._open(f"recovery probe failed: {self._last_cause}")
                return
            if self._state == self.OPEN:
                return   # already open; nothing new to learn
            self._push_outcome(True)
            self._consecutive_failures += 1
            failures = sum(self._outcomes)
            if self._consecutive_failures >= self.failure_threshold:
                self._open(f"{self._consecutive_failures} consecutive "
                           f"failures; last: {self._last_cause}")
            elif (len(self._outcomes) >= self.min_calls
                    and failures / len(self._outcomes)
                    >= self.failure_rate):
                self._open(f"failure rate {failures}/{len(self._outcomes)} "
                           f">= {self.failure_rate:g}; last: "
                           f"{self._last_cause}")

    def call(self, fn: Callable[[], Any]) -> Any:
        """Run ``fn`` under the breaker: raise ``CircuitOpenError`` without
        calling it when open, otherwise report its outcome."""
        if not self.allow():
            raise CircuitOpenError(
                f"breaker {self.name!r} is open "
                f"(last: {self._last_cause or 'unknown'})",
                retry_after_s=self.retry_after_s())
        try:
            result = fn()
        except BaseException as e:
            self.record_failure(e)
            raise
        self.record_success()
        return result

    # -- internals (call with self._lock held) -----------------------------
    def _push_outcome(self, failed: bool) -> None:
        self._outcomes.append(failed)
        if len(self._outcomes) > self.window:
            del self._outcomes[:len(self._outcomes) - self.window]

    def _open(self, reason: str) -> None:
        self._opened_at = self._clock()
        self._probe_permits = 0
        self._probe_successes = 0
        self._transition(self.OPEN, reason)

    def _transition(self, to: str, reason: str) -> None:
        frm, self._state = self._state, to
        action = {self.OPEN: "breaker_open",
                  self.HALF_OPEN: "breaker_half_open",
                  self.CLOSED: "breaker_closed"}[to]
        try:
            active_failure_log().record(
                "breaker", action, reason, point=f"breaker.{self.name}",
                breaker=self.name)
        except Exception:  # noqa: BLE001 — bookkeeping must not break calls
            pass
        try:
            from .telemetry import event
            event("breaker.transition", breaker=self.name,
                  from_state=frm, to_state=to, reason=reason)
        except Exception:  # noqa: BLE001
            pass
        if self._registry is not None:
            try:
                self._registry.counter(
                    f"breaker.{self.name}.{to}_total").inc()
            except Exception:  # noqa: BLE001
                pass


# --------------------------------------------------------------------------
# adaptive concurrency limit (AIMD)
# --------------------------------------------------------------------------

class AdaptiveConcurrencyLimit:
    """AIMD admission limit driven by observed batch latency vs. a target.

    Every completed batch calls ``observe(latency_s)``: latencies at or
    under ``target_latency_s`` grow the limit additively (``increase`` per
    observation); latencies over it shrink the limit multiplicatively
    (``decrease`` factor) — the TCP-congestion-control shape, which
    converges to the deepest queue the backend can drain within the
    latency target.  The limit is clamped to ``[min_limit, max_limit]``;
    ``max_limit`` is the static ceiling (the old ``queue_bound``) that
    still backstops the adaptive signal."""

    def __init__(self, *, target_latency_s: float, max_limit: int,
                 min_limit: int = 4, increase: float = 1.0,
                 decrease: float = 0.75,
                 initial: Optional[int] = None):
        if max_limit < 1:
            raise ValueError("max_limit must be >= 1")
        self.target_latency_s = float(target_latency_s)
        self.max_limit = int(max_limit)
        self.min_limit = max(1, min(int(min_limit), self.max_limit))
        self.increase = float(increase)
        self.decrease = float(decrease)
        if not 0.0 < self.decrease < 1.0:
            raise ValueError("decrease must be in (0, 1)")
        self._limit = float(initial if initial is not None
                            else self.max_limit)
        self._limit = min(max(self._limit, self.min_limit), self.max_limit)
        self._lock = threading.Lock()
        self._observations = 0
        self._decreases = 0

    @property
    def limit(self) -> int:
        with self._lock:
            return int(self._limit)

    def observe(self, latency_s: float) -> int:
        """Feed one batch latency; returns the updated limit."""
        with self._lock:
            self._observations += 1
            if latency_s <= self.target_latency_s:
                self._limit = min(self.max_limit,
                                  self._limit + self.increase)
            else:
                self._decreases += 1
                self._limit = max(self.min_limit,
                                  self._limit * self.decrease)
            return int(self._limit)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {"limit": int(self._limit),
                    "min_limit": self.min_limit,
                    "max_limit": self.max_limit,
                    "target_latency_s": self.target_latency_s,
                    "observations": self._observations,
                    "decreases": self._decreases}


# --------------------------------------------------------------------------
# fault injection
# --------------------------------------------------------------------------

class FaultInjector:
    """Deterministic chaos harness over named injection points.

    Production code calls ``maybe_inject(point, key=...)`` at its risky
    sites; with no injector installed that is a no-op attribute check.  A
    test installs an injector (``with inject_faults(FaultInjector(...))``)
    and selected (point, key) pairs raise ``InjectedFault``.

    Decisions are *sticky and pure*: whether (point, key) fails is a hash of
    (seed, point, key) against the point's rate — the same key fails on
    every retry (so retry exhaustion and dead-lettering are exercised) and
    the same seed reproduces the identical failure set on every run.

    ``rates``     — point → probability in [0, 1] that a key fails;
    ``fail_keys`` — point → explicit keys that always fail (deterministic
                    acceptance tests: "kill candidate 'LR' and batch 1")."""

    def __init__(self, rates: Optional[Dict[str, float]] = None,
                 fail_keys: Optional[Dict[str, Iterable[Any]]] = None,
                 seed: int = 0):
        self.rates = {k: float(v) for k, v in (rates or {}).items()}
        self.fail_keys = {p: {str(k) for k in ks}
                          for p, ks in (fail_keys or {}).items()}
        self.seed = int(seed)
        self.fired: List[Tuple[str, str]] = []   # every raise, in order
        # parallel to ``fired``: the ambient span id each fault fired
        # inside (None when tracing was off) — chaos failures point at the
        # exact span in the trace timeline
        self.fired_spans: List[Optional[str]] = []
        self._auto_counts: Dict[str, int] = {}
        self._lock = threading.Lock()

    def should_fail(self, point: str, key: Any = None) -> bool:
        if key is None:
            with self._lock:
                key = self._auto_counts.get(point, 0)
                self._auto_counts[point] = key + 1
        key = str(key)
        if key in self.fail_keys.get(point, ()):
            return True
        rate = self.rates.get(point, 0.0)
        if rate <= 0.0:
            return False
        return _stable_uniform(self.seed, point, key) < rate

    def check(self, point: str, key: Any = None) -> None:
        """Raise ``InjectedFault`` when (point, key) is armed."""
        if self.should_fail(point, key):
            from .telemetry import current_span_id
            sid = current_span_id()
            with self._lock:
                self.fired.append((point, str(key)))
                self.fired_spans.append(sid)
            err = InjectedFault(
                f"injected fault at {point!r} (key={key!r})")
            err.span_id = sid
            raise err

    # -- installation ------------------------------------------------------
    def install(self) -> "FaultInjector":
        global _INJECTOR
        _INJECTOR = self
        return self

    def uninstall(self) -> None:
        global _INJECTOR
        if _INJECTOR is self:
            _INJECTOR = None

    def __enter__(self) -> "FaultInjector":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()


_INJECTOR: Optional[FaultInjector] = None


def maybe_inject(point: str, key: Any = None) -> None:
    """Injection-point hook: no-op unless a FaultInjector is installed."""
    inj = _INJECTOR
    if inj is not None:
        inj.check(point, key)


@contextmanager
def inject_faults(injector: FaultInjector):
    """Install ``injector`` for the dynamic extent (restores the previous)."""
    global _INJECTOR
    prev = _INJECTOR
    _INJECTOR = injector
    try:
        yield injector
    finally:
        _INJECTOR = prev


# Injection points wired through the execution layer.  Keys are stable
# identifiers (candidate model name, micro-batch index, stage uid) so chaos
# decisions survive retries and reorderings.
INJECTION_POINTS = {
    "selector.candidate_fit": "one (model × grid) candidate family fit",
    "selector.candidate_metric": "scoring one fitted candidate",
    "streaming.batch": "scoring one streaming micro-batch",
    "compiled.segment": "executing one fused device segment",
    "multihost.init": "jax distributed runtime initialization",
    "checkpoint.save": "committing a model/sweep bundle (after data write, "
                       "before atomic rename)",
    "checkpoint.load": "verifying a bundle's manifest + digests on load",
    "preemption": "a candidate/batch boundary's graceful-stop check",
    "serving.batch": "scoring one coalesced serving micro-batch",
    "serving.reload": "hot-swapping a newer model version into the engine",
    "lifecycle.retrain": "starting a policy-triggered lifecycle retrain",
    "lifecycle.promote": "committing a lifecycle promotion decision (after "
                         "the holdout gate, before the bundle write)",
    "supervisor.probe": "one subprocess-isolated device availability probe",
    "supervisor.heartbeat": "one heartbeat supervision tick",
    "supervisor.chunk_stall": "one host->device streaming chunk transfer "
                              "(fires as a stalled/hung link)",
    "supervisor.device_loss": "a device dropping out of the active mesh "
                              "mid-sweep (fit or scoring)",
    "memory.device_oom": "a device allocator exhausting HBM mid-sweep "
                         "(fires as RESOURCE_EXHAUSTED; routes to the "
                         "shrink-and-retry ladder, never the mesh shrink)",
    "memory.host_pressure": "one host RSS watchdog tick (fires as a "
                            "hard-watermark reading)",
}
