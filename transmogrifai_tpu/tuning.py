"""Splitters and validators — the TPU-native re-design of the reference tuning
package (core/src/main/scala/com/salesforce/op/stages/impl/tuning/:
DataSplitter.scala, DataBalancer.scala, DataCutter.scala, OpValidator.scala:91,
OpCrossValidation.scala:42, OpTrainValidationSplit.scala).

Where the reference fan-outs k × Σ|grid| Spark jobs over a JVM thread pool
(OpValidator.scala:320-349), here each candidate fit is a compiled XLA program
over HBM-resident fold slices; homogeneous hyper-parameter grids additionally
vectorise via the models' array-level fit functions (SURVEY.md §2.6 P3).
Reference defaults preserved: NumFolds=3, Parallelism=8, stratify=false
(OpValidator.scala:372-378).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import logging

import numpy as np

from .columns import ColumnBatch
from .evaluators import OpEvaluatorBase
from .resilience import (AllCandidatesFailed, active_failure_log,
                         maybe_inject, record_failure)

logger = logging.getLogger(__name__)

# batched-metric fast-path fallbacks already logged, one per model family
# PER VALIDATE — a silent fallback could hide a real fitted-state corruption
# behind the (correct but slow) per-candidate path (VERDICT r4 next #7a).
# Scoped per-validate (reset by ``Validator.validate``): a module-lifetime
# set would suppress the note for every later train in the same process
# (lifecycle retrains, pool workers), exactly the runs where a NEW
# corruption could appear.  The FailureLog record stays unconditional.
_logged_fallback_families = set()


def _reset_logged_fallbacks() -> None:
    _logged_fallback_families.clear()


def _log_metric_fallback(family: str, exc: BaseException) -> None:
    record_failure(family, "fallback", exc, point="selector.batched_metrics")
    if family not in _logged_fallback_families:
        _logged_fallback_families.add(family)
        # warning, not debug: the default root logger must surface it
        logger.warning("batched grid-metric fast path fell back to the "
                       "per-candidate path for %s: %r", family, exc)


# --------------------------------------------------------------------------
# splitters
# --------------------------------------------------------------------------

@dataclass
class SplitterSummary:
    """Metadata recorded by preValidationPrepare (≙ SplitterSummary)."""
    splitter: str = ""
    info: Dict[str, Any] = field(default_factory=dict)


class Splitter:
    """≙ tuning/Splitter.scala: optional test-holdout + per-class preparation."""

    def __init__(self, seed: int = 42, reserve_test_fraction: float = 0.0):
        self.seed = int(seed)
        self.reserve_test_fraction = float(reserve_test_fraction)
        self.summary: Optional[SplitterSummary] = None

    def split(self, batch: ColumnBatch, label: str) -> Tuple[ColumnBatch, ColumnBatch]:
        n = len(batch)
        rng = np.random.default_rng(self.seed)
        perm = rng.permutation(n)
        n_test = int(round(n * self.reserve_test_fraction))
        return batch.take_rows(perm[n_test:]), batch.take_rows(perm[:n_test])

    def pre_validation_prepare(self, batch: ColumnBatch, label: str) -> ColumnBatch:
        self.summary = SplitterSummary(type(self).__name__)
        return batch

    def validation_prepare(self, batch: ColumnBatch, label: str) -> ColumnBatch:
        return batch

    def validation_prepare_weights(self, y: np.ndarray,
                                   w: np.ndarray) -> np.ndarray:
        """Weight-space variant of ``validation_prepare`` for the static-shape
        CV path: adjust per-row training weights (0 == excluded) instead of
        materialising a resampled batch — keeps one HBM-resident X with no
        per-fold reshapes."""
        return w


class DataSplitter(Splitter):
    """≙ DataSplitter: plain random split, no rebalancing."""


class DataBalancer(Splitter):
    """≙ DataBalancer.scala: resample a binary label towards a minimum
    ``sample_fraction`` of the minority class, capped at
    ``max_training_sample`` rows.

    Reference semantics reproduced exactly (DataBalancer.scala:76-160):

    * already balanced (minority fraction ≥ ``sample_fraction``) → no
      resampling; only a global down-sample when the data exceeds the cap;
    * minority below the cap's share → UP-sample it by the largest integer
      multiplier from {100, 50, 10, 5, 4, 3, 2} that stays under both the
      target fraction and the cap (with replacement), then down-sample the
      majority to hit the fraction;
    * otherwise down-sample BOTH classes to the capped size at the target
      fraction.
    """

    def __init__(self, sample_fraction: float = 0.1,
                 max_training_sample: int = 1_000_000, seed: int = 42,
                 reserve_test_fraction: float = 0.0):
        super().__init__(seed, reserve_test_fraction)
        self.sample_fraction = float(sample_fraction)
        self.max_training_sample = int(max_training_sample)

    @staticmethod
    def get_proportions(small: float, big: float, sample_f: float,
                        max_training_sample: int) -> Tuple[float, float]:
        """(downSample, upSample) fractions (≙ getProportions,
        DataBalancer.scala:84-115)."""

        def check_up(mult: int) -> bool:
            return (mult * small * (1.0 - sample_f) < sample_f * big
                    and max_training_sample * sample_f > small * mult)

        if small < max_training_sample * sample_f:
            up = next((float(m) for m in (100, 50, 10, 5, 4, 3, 2)
                       if check_up(m)), 1.0)
            down = (small * up / sample_f - small * up) / big
            return down, up
        up = (max_training_sample * sample_f) / small
        down = (1.0 - sample_f) * max_training_sample / big
        return down, up

    def _plan(self, y: np.ndarray) -> Dict[str, Any]:
        """≙ estimate (DataBalancer.scala:130-175): decide fractions and
        record the DataBalancerSummary fields."""
        pos = int((y > 0.5).sum())
        neg = int(len(y) - pos)
        total = max(pos + neg, 1)
        sample_f = self.sample_fraction
        is_pos_small = pos < neg
        small, big = (pos, neg) if is_pos_small else (neg, pos)
        if small / total >= sample_f:
            frac = (self.max_training_sample / total
                    if self.max_training_sample < total else 1.0)
            plan = {"balanced": True, "fraction": frac,
                    "is_pos_small": is_pos_small, "up": 0.0, "down": frac}
        else:
            down, up = self.get_proportions(small, big, sample_f,
                                            self.max_training_sample)
            plan = {"balanced": False, "is_pos_small": is_pos_small,
                    "up": up, "down": down}
        self.summary = SplitterSummary("DataBalancer", {
            "positiveLabels": pos, "negativeLabels": neg,
            "desiredFraction": sample_f,
            "upSamplingFraction": 0.0 if plan["balanced"] else plan["up"],
            "downSamplingFraction": plan["down"]})
        return plan

    def pre_validation_prepare(self, batch, label):
        self._plan(np.asarray(batch[label].values, dtype=np.float64))
        return batch

    def validation_prepare(self, batch, label):
        """Physically resample rows (≙ rebalance, DataBalancer.scala:
        sample with replacement for up > 1, plain subsample otherwise)."""
        y = np.asarray(batch[label].values, dtype=np.float64)
        plan = self._plan(y)
        rng = np.random.default_rng(self.seed)
        n = len(y)
        if plan["balanced"]:
            if plan["fraction"] >= 1.0:
                return batch
            keep = np.flatnonzero(rng.random(n) < plan["fraction"])
            return batch.take_rows(keep)
        small_mask = ((y > 0.5) == plan["is_pos_small"])
        small_idx = np.flatnonzero(small_mask)
        big_idx = np.flatnonzero(~small_mask)
        big_keep = big_idx[rng.random(len(big_idx)) < plan["down"]]
        up = plan["up"]
        if up > 1.0:
            # with replacement at rate `up` ≈ per-row Poisson(up) copies
            reps = rng.poisson(up, len(small_idx))
            small_keep = np.repeat(small_idx, reps)
        elif up == 1.0:
            small_keep = small_idx
        else:
            small_keep = small_idx[rng.random(len(small_idx)) < up]
        keep = np.concatenate([small_keep, big_keep])
        rng.shuffle(keep)
        return batch.take_rows(keep)

    def validation_prepare_weights(self, y, w):
        """Weight-space variant for the static-shape CV path: up-sampling
        becomes a per-row Poisson weight multiplier (the bootstrap analog of
        sampling with replacement); down-sampling zeroes a random subset."""
        idx = np.flatnonzero(w > 0)
        if not len(idx):
            return w
        plan = self._plan_cached(y, idx)
        rng = np.random.default_rng(self.seed)
        out = np.zeros_like(w)
        if plan["balanced"]:
            if plan["fraction"] >= 1.0:
                return w
            keep = idx[rng.random(len(idx)) < plan["fraction"]]
            out[keep] = w[keep]
            return out
        small_mask = ((y[idx] > 0.5) == plan["is_pos_small"])
        small_idx = idx[small_mask]
        big_idx = idx[~small_mask]
        big_keep = big_idx[rng.random(len(big_idx)) < plan["down"]]
        out[big_keep] = w[big_keep]
        up = plan["up"]
        if up > 1.0:
            reps = rng.poisson(up, len(small_idx)).astype(w.dtype)
            out[small_idx] = w[small_idx] * reps
        elif up == 1.0:
            out[small_idx] = w[small_idx]
        else:
            small_keep = small_idx[rng.random(len(small_idx)) < up]
            out[small_keep] = w[small_keep]
        return out

    def _plan_cached(self, y: np.ndarray, idx: np.ndarray) -> Dict[str, Any]:
        return self._plan(np.asarray(y, dtype=np.float64)[idx])


class DataCutter(Splitter):
    """≙ DataCutter.scala: multiclass — keep at most ``max_label_categories``
    labels each with fraction ≥ ``min_label_fraction``; drop other rows and
    record dropped labels."""

    def __init__(self, max_label_categories: int = 100,
                 min_label_fraction: float = 0.0, seed: int = 42,
                 reserve_test_fraction: float = 0.0):
        super().__init__(seed, reserve_test_fraction)
        self.max_label_categories = int(max_label_categories)
        self.min_label_fraction = float(min_label_fraction)
        self.labels_kept: List[float] = []
        self.labels_dropped: List[float] = []

    def pre_validation_prepare(self, batch, label):
        y = np.asarray(batch[label].values, dtype=np.float64)
        vals, counts = np.unique(y, return_counts=True)
        frac = counts / max(len(y), 1)
        order = np.argsort(-counts, kind="mergesort")
        keep = [v for i, v in zip(order, vals[order])
                if frac[i] >= self.min_label_fraction][:self.max_label_categories]
        keep_set = set(keep)
        self.labels_kept = sorted(keep_set)
        self.labels_dropped = sorted(set(vals.tolist()) - keep_set)
        self.summary = SplitterSummary("DataCutter", {
            "labelsKept": self.labels_kept, "labelsDropped": self.labels_dropped})
        return batch

    def validation_prepare(self, batch, label):
        if not self.labels_dropped:
            return batch
        y = np.asarray(batch[label].values, dtype=np.float64)
        mask = np.isin(y, np.asarray(self.labels_kept))
        return batch.take_rows(np.flatnonzero(mask))

    def validation_prepare_weights(self, y, w):
        if not self.labels_dropped:
            return w
        mask = np.isin(y, np.asarray(self.labels_kept))
        return np.where(mask, w, 0.0).astype(w.dtype)


# --------------------------------------------------------------------------
# validators
# --------------------------------------------------------------------------

_GRID_MARGINS_JIT = None


def _grid_margins(X, C, b):
    """[N, K] linear margins for K candidates in one dispatch; bf16 feature
    storage converts inside the matmul (f32 accumulation), nothing [N, D]
    materializes."""
    global _GRID_MARGINS_JIT
    if _GRID_MARGINS_JIT is None:
        import jax
        import jax.numpy as jnp

        @jax.jit
        def fn(X, C, b):
            return jnp.einsum("nd,kd->nk", X, C,
                              preferred_element_type=jnp.float32) + b[None, :]
        _GRID_MARGINS_JIT = fn
    return _GRID_MARGINS_JIT(X, C, b)


_MULTI_PRED_JIT = None


def _multinomial_pred_grid(X, C3, B):
    """[N, K] argmax class predictions for K multinomial candidates in one
    dispatch (coef stack [K, C, D], intercepts [K, C]).  Softmax is
    monotone per row, so argmax over raw margins reproduces each model's
    prediction exactly."""
    global _MULTI_PRED_JIT
    if _MULTI_PRED_JIT is None:
        import jax
        import jax.numpy as jnp

        @jax.jit
        def fn(X, C3, B):
            m = jnp.einsum("nd,kdc->nkc", X, C3,
                           preferred_element_type=jnp.float32) + B[None]
            return jnp.argmax(m, axis=-1).astype(jnp.int32)
        _MULTI_PRED_JIT = fn
    return _MULTI_PRED_JIT(X, C3, B)


# fit-program row-count canonicalization (ISSUE 4 compile reuse): pad N up a
# geometric ladder with zero-weight rows so re-trains at nearby sizes hit the
# SAME compiled fit executable.  Zero-weight padding is exact for the linear
# solvers (every reduction is weight-normalized — see
# models/solvers.linear_grid_fit); tree fitters bin features with unweighted
# quantiles, so only estimators declaring ``weighted_pad_exact`` opt in.
_FIT_PAD_FLOOR = 4096
_FIT_PAD_STEP = 1.25
_FIT_PAD_QUANTUM = 256


def _fit_pad_rows(n: int) -> int:
    """Smallest ladder rung >= n.  n <= the floor returns n unchanged, so
    small fixtures (and every tier-1 test) keep bit-identical shapes."""
    if n <= _FIT_PAD_FLOOR:
        return int(n)
    rung = _FIT_PAD_FLOOR
    while rung < n:
        rung = int(-(-int(rung * _FIT_PAD_STEP) // _FIT_PAD_QUANTUM)
                   * _FIT_PAD_QUANTUM)
    return rung


def _fit_padding_enabled() -> bool:
    """Shape canonicalization only pays off with a persistent compile cache
    to hit, so it rides the TRANSMOGRIFAI_COMPILE_CACHE opt-in."""
    import os
    cc = os.environ.get("TRANSMOGRIFAI_COMPILE_CACHE")
    return bool(cc) and cc != "0"


_FOLD_MASK_FNS: Dict[int, Any] = {}

# uint8 fold-assignment sentinels: 255 = "in no validation fold" (a TVS row
# outside the held-out slice — it trains in every fold), 254 = "zero-weight
# pad row" (mesh device-divisibility quantum / ladder rung — it belongs to
# NO fold, training or validation)
_NO_FOLD = 255
_PAD_FOLD = 254


def _fold_masks_from_assignment(assign, n_folds: int):
    """[N] uint8 validation-fold assignment → (train weights [F, N],
    validation masks [F, N]) built ON DEVICE: the host link carries one
    byte per row instead of the materialized masks.  A sharded assignment
    propagates its row sharding into the masks (axis 1), so the mesh path
    never materializes [F, N] weights on the host."""
    import jax
    import jax.numpy as jnp

    fn = _FOLD_MASK_FNS.get(n_folds)
    if fn is None:
        @jax.jit
        def fn(a):
            f = jnp.arange(n_folds, dtype=jnp.int32)[:, None]
            ai = a.astype(jnp.int32)[None, :]
            tr = ((ai != f) & (ai != _PAD_FOLD)).astype(jnp.float32)
            return tr, (ai == f).astype(jnp.float32)
        _FOLD_MASK_FNS[n_folds] = fn
    return fn(assign)


@dataclass
class ModelCandidate:
    """One estimator + its hyper-parameter grid (≙ (estimator, Array[ParamMap]))."""
    estimator: Any                      # PredictorEstimator (unwired is fine)
    grid: List[Dict[str, Any]] = field(default_factory=lambda: [{}])
    name: Optional[str] = None

    @property
    def model_name(self) -> str:
        return self.name or type(self.estimator).__name__


@dataclass
class ValidatedCandidate:
    model_name: str
    params: Dict[str, Any]
    metric_values: List[float]
    candidate_index: int = 0   # identity: two candidates may share a name
    # successive halving pruned this grid point after the fold-0 screen:
    # metric_values holds the fold-0 metric only and the point is excluded
    # from final winner selection (full-k-fold means only)
    raced_out: bool = False

    @property
    def mean_metric(self) -> float:
        vals = [v for v in self.metric_values if np.isfinite(v)]
        return float(np.mean(vals)) if vals else float("nan")


@dataclass
class ValidationResult:
    best: ModelCandidate                 # winning estimator with params applied
    best_params: Dict[str, Any]
    best_metric: float
    all_results: List[ValidatedCandidate]
    validation_type: str
    metric_name: str
    is_larger_better: bool


class OpValidator:
    """Base validator (≙ OpValidator.scala:91).

    ``validate`` fits every (candidate × grid-point) on each train split and
    scores on the held-out split with ``evaluator``; individual fit failures
    are tolerated (CHANGELOG 0.6.x: "robust to failing models") — a failed fit
    contributes NaN for that split and the candidate is skipped if it never
    succeeds.
    """

    validation_type = "validator"

    def __init__(self, evaluator: OpEvaluatorBase, seed: int = 42,
                 stratify: bool = False, parallelism: int = 8,
                 racing: Optional[bool] = None,
                 racing_eta: Optional[float] = None,
                 racing_min_survivors: Optional[int] = None):
        self.evaluator = evaluator
        self.seed = int(seed)
        self.stratify = bool(stratify)
        self.parallelism = int(parallelism)
        # successive-halving sweep racing (ISSUE 4): None defers to
        # DefaultSelectorParams so OpParams/selector factories can retune
        # the fleet-wide defaults without touching every validator ctor
        self.racing = racing
        self.racing_eta = racing_eta
        self.racing_min_survivors = racing_min_survivors
        # per-family (folds, rows, lanes) of the last batched fit block —
        # the selector's winner refit reuses the SAME compiled executable
        self.family_fit_meta: Dict[str, Dict[str, Any]] = {}

    def _racing_config(self) -> Tuple[bool, float, int]:
        """(enabled, eta, min_survivors) with DefaultSelectorParams filling
        unset knobs.  Lazy import: selector.py imports this module."""
        from .selector import DefaultSelectorParams as P
        enabled = (self.racing if self.racing is not None
                   else bool(getattr(P, "RACING", True)))
        eta = float(self.racing_eta if self.racing_eta is not None
                    else getattr(P, "RACING_ETA", 3.0))
        mins = int(self.racing_min_survivors
                   if self.racing_min_survivors is not None
                   else getattr(P, "RACING_MIN_SURVIVORS", 2))
        return bool(enabled), max(eta, 1.0 + 1e-9), max(mins, 1)

    # -- split generation -------------------------------------------------
    def splits(self, y: np.ndarray) -> List[Tuple[np.ndarray, np.ndarray]]:
        raise NotImplementedError

    def _stratified_perm(self, y: np.ndarray, rng) -> np.ndarray:
        """Interleave per-class shuffled indices so every contiguous cut is
        label-balanced (≙ stratifyKFolds, OpCrossValidation.scala:184)."""
        order = []
        for v in np.unique(y):
            idx = np.flatnonzero(y == v)
            rng.shuffle(idx)
            order.append(idx)
        # round-robin interleave
        out = []
        iters = [iter(ix) for ix in order]
        while iters:
            nxt = []
            for it in iters:
                try:
                    out.append(next(it))
                    nxt.append(it)
                except StopIteration:
                    pass
            iters = nxt
        return np.asarray(out, dtype=np.int64)

    def _maybe_mesh(self, n_rows: int, pad: bool = False):
        """Shared data-axis mesh policy (parallel.mesh.maybe_data_mesh).
        ``pad=True`` lets the sweep take the mesh on non-divisible row counts
        (the sweep appends zero-weight pad rows, which is exact for
        ``weighted_pad_exact`` families)."""
        from .parallel.mesh import maybe_data_mesh
        return maybe_data_mesh(n_rows, pad=pad)

    def _record_grid_metrics_batched(self, cand, ci, fitted_grid, X, y_dev,
                                     va_masks_dev, record) -> bool:
        """Score a LINEAR family's whole (fold × grid) block with ONE matmul
        + ONE vmapped metric program + deferred scalars — K per-candidate
        metric dispatches (each a link round trip of queue latency) collapse
        to a single pair.  AUC metrics are rank-invariant, so raw margins
        replace per-model sigmoid scores exactly.  Returns False when the
        family/evaluator has no batched form (caller keeps the per-candidate
        path)."""
        import jax
        import jax.numpy as jnp

        if (self.evaluator is None
                or type(self.evaluator).evaluate_masked_grid
                is OpEvaluatorBase.evaluate_masked_grid):
            return False
        F = len(va_masks_dev)
        G = len(cand.grid)
        kinds = {fitted.get("kind") if isinstance(fitted, dict) else None
                 for row in fitted_grid for fitted in row}
        if kinds <= {"forest", "gbt"}:
            return self._record_tree_grid_metrics(cand, ci, fitted_grid, X,
                                                  y_dev, va_masks_dev, record)
        panel_input = getattr(self.evaluator, "grid_panel_input", "scores")
        multinomial = kinds == {"multinomial"}
        if multinomial and panel_input != "predictions":
            return False    # C margin columns don't collapse to one score
        coefs, intercepts = [], []
        for f in range(F):
            for gi in range(G):
                fitted = fitted_grid[f][gi]
                if not isinstance(fitted, dict) or "coef" not in fitted:
                    return False
                c = fitted["coef"]
                if multinomial:
                    if (fitted.get("kind") != "multinomial"
                            or getattr(c, "ndim", 0) != 2):
                        return False
                elif (fitted.get("kind") not in ("binary", "svc",
                                                 "regression")
                        or getattr(c, "ndim", 1) != 1):
                    return False
                coefs.append(c)
                intercepts.append(fitted.get("intercept", 0.0))
        try:
            from .sparse.matrix import SparseMatrix
            if multinomial:
                # multinomial coef is stored [D, C] (see LinearPredictionModel)
                C3 = jnp.stack([jnp.asarray(c, jnp.float32) for c in coefs])
                B = jnp.stack([jnp.asarray(i, jnp.float32).reshape(-1)
                               for i in intercepts])       # [F*G, C]
                if isinstance(X, SparseMatrix):
                    K_, D_, Cc = C3.shape
                    M = jnp.transpose(C3, (1, 0, 2)).reshape(D_, K_ * Cc)
                    m = (X @ M).reshape(X.shape[0], K_, Cc) + B[None]
                    S = jnp.argmax(m, axis=-1).astype(jnp.int32)
                else:
                    S = _multinomial_pred_grid(X, C3, B)   # [N, F*G] int32
            else:
                C = jnp.stack([jnp.asarray(c, jnp.float32) for c in coefs])
                b = jnp.stack([jnp.asarray(i, jnp.float32).reshape(-1)[0]
                               for i in intercepts])
                if isinstance(X, SparseMatrix):
                    # sparse margins: one sp_matmat over the COO entry
                    # stream — the dense einsum would need the [N, D] matrix
                    # that never materializes on the sparse path
                    S = (X @ C.T) + b[None, :]             # [N, F*G]
                else:
                    S = _grid_margins(X, C, b)             # [N, F*G]
                if panel_input == "predictions":
                    if kinds <= {"binary", "svc"}:
                        # hard class ids: p1 > 0.5  <=>  margin > 0
                        S = (S > 0).astype(jnp.int32)
                    elif kinds != {"regression"}:
                        return False
                    # regression margins ARE the predictions — use as-is
            # the whole (fold × grid) metric panel as ONE program when the
            # evaluator supports it — masks stay [F, N] (no per-grid-point
            # mask HBM duplication in the near-capacity regime), and the F
            # per-fold dispatches + eager S slices collapse into one
            per_fold = None
            try:
                W = (jnp.stack(list(va_masks_dev))
                     if not hasattr(va_masks_dev, "ndim") else va_masks_dev)
                panel = self.evaluator.evaluate_masked_fold_grid(
                    y_dev, S.reshape(S.shape[0], F, G), W)
                if (panel is not None
                        and getattr(panel, "shape", ()) == (F, G)):
                    per_fold = list(panel)
            except Exception as panel_exc:  # noqa: BLE001 — e.g. HBM OOM on
                # the fused [N, F, G] panel; the per-fold loop below needs
                # only 1/F of that score memory at a time, so degrade to it
                # instead of abandoning the batched path entirely
                record_failure(cand.model_name, "degraded", panel_exc,
                               point="selector.fused_panel")
            if per_fold is None:
                # per-fold fallback: one grid-metric program per fold,
                # sharing the fold's single [N] validation mask
                per_fold = []
                for f in range(F):
                    vals = self.evaluator.evaluate_masked_grid(
                        y_dev, S[:, f * G:(f + 1) * G], va_masks_dev[f])
                    if vals is None or getattr(vals, "shape", (0,)) != (G,):
                        return False   # wrong-shape result must not record
                    per_fold.append(vals)
            for f in range(F):
                for gi, params in enumerate(cand.grid):
                    record(cand, ci, gi, params, per_fold[f][gi])
            return True
        except Exception as e:  # noqa: BLE001 — optimization only; fall back
            _log_metric_fallback(cand.model_name, e)
            return False

    def _record_tree_grid_metrics(self, cand, ci, fitted_grid, X, y_dev,
                                  va_masks_dev, record) -> bool:
        """Tree-family analog of the batched linear metrics: within each
        (fold, tree-shape) group, the members' tree stacks concatenate and
        ONE blocked walk produces per-member leaf SUMS — rank-equivalent to
        each candidate's probability (gini leaves sum to 1 per tree) or GBT
        margin (positive affine in the leaf sum), so the AUC metrics match
        the per-candidate path.  Replaces one predict+metric dispatch chain
        per (fold × grid point) with one per (fold × shape group)."""
        from collections import defaultdict

        import jax.numpy as jnp

        from .models.trees import predict_trees_sum_grouped

        F = len(va_masks_dev)
        G = len(cand.grid)
        panel_input = getattr(self.evaluator, "grid_panel_input", "scores")
        groups = defaultdict(list)
        for f in range(F):
            for gi in range(G):
                fitted = fitted_grid[f][gi]
                if not isinstance(fitted, dict) or fitted.get("kind") not in (
                        "forest", "gbt"):
                    return False
                task = fitted.get("task", "classification")
                if task == "regression":
                    if panel_input != "predictions":
                        return False   # scores evaluator on regression leaves
                elif fitted["kind"] == "forest" and fitted.get(
                        "n_classes", 2) != 2 and panel_input != "predictions":
                    return False   # multiclass forest needs a prediction panel
                shp = tuple(np.shape(fitted["feature"]))
                if len(shp) != 2:
                    return False
                groups[(f, fitted["kind"], shp,
                        int(fitted["max_depth"]))].append((gi, fitted))
        try:
            results = {}
            for (f, kind, _shp, md), members in groups.items():
                K = len(members)
                feat = jnp.concatenate(
                    [jnp.asarray(m["feature"]) for _, m in members])
                thr = jnp.concatenate(
                    [jnp.asarray(m["threshold"]) for _, m in members])
                lf = jnp.concatenate(
                    [jnp.asarray(m["is_leaf"]) for _, m in members])
                lv = jnp.concatenate(
                    [jnp.asarray(m["leaf"]) for _, m in members])
                sums = predict_trees_sum_grouped(X, feat, thr, lf, lv,
                                                 md + 1, K)   # [N, K, V]
                task = members[0][1].get("task", "classification")
                if kind == "forest":
                    if task == "regression":
                        # mean leaf value IS the prediction — exact
                        S = sums[..., 0] / float(_shp[0])
                    elif panel_input == "predictions":
                        # argmax of summed per-class leaf mass == argmax of
                        # the normalized mean probs (positive scaling)
                        S = jnp.argmax(sums, axis=-1).astype(jnp.int32)
                    else:
                        S = sums[..., 1]
                else:
                    import jax
                    eta = jnp.asarray([float(m["eta"]) for _, m in members],
                                      jnp.float32)
                    base = jnp.asarray([float(m["base"]) for _, m in members],
                                       jnp.float32)
                    margin = base[None, :] + eta[None, :] * sums[..., 0]
                    if task == "regression":
                        S = margin                  # prediction, exact
                    elif panel_input == "predictions":
                        # sigmoid(margin) > 0.5  <=>  margin > 0
                        S = (margin > 0).astype(jnp.int32)
                    else:
                        # reproduce the per-candidate path's sigmoid(margin)
                        # EXACTLY — raw sums rank identically in exact math,
                        # but f32 sigmoid saturation creates tie groups the
                        # raw sums would not, shifting AUC on confidently-
                        # separated data
                        S = jax.nn.sigmoid(margin)
                vals = self.evaluator.evaluate_masked_grid(
                    y_dev, S, va_masks_dev[f])
                if vals is None or getattr(vals, "shape", (0,)) != (K,):
                    return False
                for j, (gi, _) in enumerate(members):
                    results[(f, gi)] = vals[j]
            for f in range(F):
                for gi, params in enumerate(cand.grid):
                    record(cand, ci, gi, params, results[(f, gi)])
            return True
        except Exception as e:  # noqa: BLE001 — optimization only; fall back
            _log_metric_fallback(cand.model_name, e)
            return False

    # -- main entry -------------------------------------------------------
    def validate(self, candidates: Sequence[ModelCandidate], batch: ColumnBatch,
                 label: str, features: str,
                 in_fold_dag: Optional[List[List[Any]]] = None,
                 splitter: Optional[Splitter] = None) -> ValidationResult:
        """Run the sweep with degrade-to-surviving-mesh recovery: a mid-sweep
        device loss (typed ``DeviceLostError``/``TransferStallError`` or a
        runtime UNAVAILABLE/DEVICE_LOST) shrinks the supervisor's
        surviving-device cap, rebuilds the mesh policy over the survivors
        (``maybe_data_mesh`` consults the cap, re-padding to the new device
        quantum), and re-enters the sweep — which resumes from the
        ``SweepCheckpoint`` candidate boundary, replaying already-scored
        families instead of refitting them.  Bounded by
        TRANSMOGRIFAI_SWEEP_RECOVERIES (0 with ``--no-supervisor``: the
        error propagates unchanged).

        Classified device-memory exhaustion (``is_memory_exhaustion``:
        RESOURCE_EXHAUSTED / allocator messages — deliberately disjoint
        from device loss) takes the OTHER recovery: the deterministic
        shrink ladder (halve streaming chunks → partition the candidate
        grid → collapse the model axis → per-candidate fallback), one rung
        per retry, resuming from the same checkpoint.  Bounded by
        TRANSMOGRIFAI_OOM_RECOVERIES; an exhausted ladder raises typed
        ``MemoryExhaustedError`` with the attempted plan attached."""
        from .parallel import hostgroup as _hostgroup
        from .parallel import memory as _memory
        from .parallel import supervisor as _supervisor
        from .telemetry import span
        # inside a multi-process host group the sweep span carries the rank
        # so merged traces attribute each sweep lane to its host
        _hg_attrs = {}
        if _hostgroup.hostgroup_env_present():
            _hg_attrs = {"hostgroup_rank": _hostgroup.current_rank(),
                         "hostgroup_world": _hostgroup.group_world_size()}
        # the one-per-family fallback warning is scoped to THIS validate:
        # a second train in the same process surfaces its own fallbacks
        _reset_logged_fallbacks()
        from .obsv import BOARD
        attempt = 0
        oom_attempt = 0
        while True:
            self._sweep_attempt = attempt
            self._oom_attempt = oom_attempt
            # control-plane seam: the retry loop is the coarse boundary —
            # /statusz shows which recovery lane the sweep is in
            BOARD.publish(phase="sweep", sweepAttempt=attempt,
                          oomAttempt=oom_attempt,
                          candidateFamilies=len(candidates),
                          gridPoints=sum(len(c.grid) for c in candidates))
            # the RSS watchdog's hard watermark surfaces HERE, on the
            # governed thread, where a typed error can be handled — not as
            # a kernel OOM-kill of an arbitrary victim
            _memory.check_host_pressure()
            try:
                with span("selector.sweep", candidates=len(candidates),
                          validation_type=self.validation_type,
                          grid_points=sum(len(c.grid) for c in candidates),
                          attempt=attempt, oom_attempt=oom_attempt,
                          **_hg_attrs):
                    return self._validate_impl(candidates, batch, label,
                                               features,
                                               in_fold_dag=in_fold_dag,
                                               splitter=splitter)
            except Exception as e:  # noqa: BLE001 — classify, maybe recover
                if _supervisor.is_device_loss(e):
                    if attempt >= _supervisor.max_sweep_recoveries():
                        raise
                    _supervisor.note_sweep_device_loss(e, attempt=attempt,
                                                       stage="validator")
                    attempt += 1
                    continue
                if _memory.is_memory_exhaustion(e):
                    if not _memory.memory_governor_enabled():
                        raise   # --no-memory-governor: propagate unchanged
                    if oom_attempt >= _memory.max_oom_recoveries():
                        raise _memory.as_memory_exhausted(e) from e
                    _memory.note_sweep_memory_exhaustion(
                        e, attempt=oom_attempt, stage="validator")
                    oom_attempt += 1
                    continue
                raise

    def _validate_impl(self, candidates: Sequence[ModelCandidate],
                       batch: ColumnBatch, label: str, features: str,
                       in_fold_dag: Optional[List[List[Any]]] = None,
                       splitter: Optional[Splitter] = None
                       ) -> ValidationResult:
        """Run the CV/TVS grid.

        The fast path (no in-fold DAG) keeps ONE data matrix in HBM and turns
        folds into per-row weight masks, so each candidate family trains its
        whole (fold × grid) block as a single batched XLA program
        (``fit_arrays_grid``) with zero fold-shape recompiles — the TPU
        re-design of the reference's k×Σ|grid| Spark-job fan-out
        (OpValidator.scala:320-349).  ``splitter.validation_prepare_weights``
        applies Balancer/Cutter preparation to each fold's *training* rows
        (scoring stays on the untouched validation slice), matching the
        reference flow.
        """
        import copy

        from .dag import apply_dag, fit_dag

        y_all = np.asarray(batch[label].values, dtype=np.float64)
        splits = self.splits(y_all)

        # -- successive-halving racing plan (ISSUE 4) ----------------------
        # Screen the full grid on fold 0 only, prune to the top 1/eta per
        # family (floored at min_survivors), run the remaining folds for
        # survivors only.  The parity guard keeps any family whose survivor
        # floor covers its whole grid on the exact full-CV path — tiny grids
        # are bit-identical to an unraced sweep.
        racing_on, racing_eta, racing_min_surv = self._racing_config()
        # racing runs on the mesh-sharded path too: round A/B fits are the
        # same batched programs with a fold-sliced weight block, and GSPMD
        # shards them identically — no single-device carve-out needed
        race_path_ok = not in_fold_dag and len(splits) >= 2
        if racing_on and not race_path_ok:
            # the flag is on by default — say WHY this sweep runs unraced
            # instead of silently ignoring it (ISSUE 4 satellite)
            reason = ("in-fold DAG refits feature stages per fold"
                      if in_fold_dag else
                      "single train/validation split (racing needs >= 2 "
                      "folds)")
            record_failure("validator", "degraded",
                           f"racing disabled: {reason}",
                           point="selector.racing",
                           validation_type=self.validation_type)

        def _survivor_count(G: int) -> int:
            return max(racing_min_surv, int(np.ceil(G / racing_eta)))

        raced_flags = [racing_on and race_path_ok
                       and _survivor_count(len(c.grid)) < len(c.grid)
                       for c in candidates]

        def _racing_sig(ci: int) -> Dict[str, Any]:
            if not raced_flags[ci]:
                return {"enabled": False}
            return {"enabled": True, "eta": racing_eta,
                    "minSurvivors": racing_min_surv}

        results: Dict[Tuple[str, int], ValidatedCandidate] = {}
        # device-scalar metrics are recorded lazily and pulled host-side in
        # ONE stacked transfer at the end — a per-candidate float() costs a
        # full host-link round trip each (~0.1 s on a tunneled TPU)
        deferred: List[Tuple[Any, list]] = []

        # resumable sweep: candidates already completed in the ambient sweep
        # checkpoint replay their scores instead of re-fitting.  Fast path
        # only — the in-fold-DAG path accumulates each candidate's metrics
        # across several fold groups, so a per-family snapshot would persist
        # half-filled metric lists.
        from .checkpoint import (SweepCheckpoint, TrainingPreempted,
                                 active_sweep_checkpoint, shutdown_requested)
        sweep_cp = None if in_fold_dag else active_sweep_checkpoint()
        sweep_sigs: List[str] = []
        replayed: set = set()
        preempted: List[str] = []
        if sweep_cp is not None:
            for ci, cand in enumerate(candidates):
                sig = SweepCheckpoint.candidate_signature(
                    cand.model_name, ci, cand.grid, racing=_racing_sig(ci))
                sweep_sigs.append(sig)
                stored = sweep_cp.results_for(sig)
                if stored is None:
                    continue
                replayed.add(ci)
                for gi, r in enumerate(stored):
                    key = (cand.model_name, ci * 10000 + gi)
                    results[key] = ValidatedCandidate(
                        cand.model_name, dict(r.get("params") or {}),
                        [float(v) for v in (r.get("metricValues") or [])],
                        candidate_index=ci,
                        raced_out=bool(r.get("racedOut", False)))
                record_failure(cand.model_name, "resumed",
                               f"replayed {len(stored)} grid point(s) from "
                               "sweep checkpoint", point="checkpoint.load",
                               candidate_index=ci)
        live = [ci for ci in range(len(candidates)) if ci not in replayed]
        _REPLAYED = object()     # sentinel fitted_grid: scores came from cp
        _PREEMPTED = object()    # sentinel fitted_grid: stop won the boundary

        def record(cand, ci, gi, params, metric):
            key = (cand.model_name, ci * 10000 + gi)
            if key not in results:
                results[key] = ValidatedCandidate(
                    cand.model_name, dict(params), [], candidate_index=ci)
            vals = results[key].metric_values
            if isinstance(metric, jax.Array):
                vals.append(float("nan"))      # patched by the batched pull
                deferred.append((metric, (vals, len(vals) - 1)))
            else:
                vals.append(float(metric))

        def make_model(cand, params, fitted):
            est = cand.estimator
            return est.model_cls(fitted=fitted, **{**est._params, **params})

        def device_metric(cand, params, fitted, X_dev, y_dev, w_dev):
            """Score a candidate entirely on device (see metrics_device);
            None → caller falls back to the host path.  Device scalars are
            returned as-is (defer=True) and pulled in one batch afterwards."""
            try:
                model = make_model(cand, params, fitted)
                if not hasattr(model, "device_scores"):
                    return None
                return self.evaluator.evaluate_masked(
                    y_dev, model.device_scores(X_dev), w_dev, defer=True)
            except Exception:  # noqa: BLE001
                return None

        def host_metric(cand, params, fitted, X_va, y_va):
            try:
                maybe_inject("selector.candidate_metric", key=cand.model_name)
                model = make_model(cand, params, fitted)
                pred = model.predict_arrays(X_va)
                return self.evaluator.evaluate(y_va, pred)
            except Exception as e:  # noqa: BLE001 — candidate robustness
                from .parallel.memory import is_memory_exhaustion
                from .parallel.supervisor import is_device_loss
                if is_device_loss(e) or is_memory_exhaustion(e):
                    raise   # sweep-level recovery, not a NaN score
                record_failure(cand.model_name, "skipped", e,
                               point="selector.candidate_metric",
                               params=dict(params))
                return float("nan")

        # (X, fold splits) groups: shared X across folds normally; per-fold X
        # when feature stages must be refit inside the fold (leakage guard,
        # ≙ OpCrossValidation.validate:87-147 DAG copy+refit).  A generator so
        # only one fold's full-size matrix is resident at a time.
        def _col_values(b):
            """Feature matrix in its native residency: device arrays stay on
            device (the host link is the bottleneck on real TPU hardware);
            sparse matrices pass through — densifying one here is exactly
            the [N, num_hashes] blow-up the representation avoids."""
            v = b[features].values
            if isinstance(v, (jax.Array, SparseMatrix)):
                return v
            return np.asarray(v, dtype=np.float32)

        def fold_groups():
            if not live:
                # every candidate replayed from the sweep checkpoint — no
                # data matrix, fold masks, or device transfers needed
                return
            if in_fold_dag:
                from .telemetry import span as _span
                for f, (tr_idx, va_idx) in enumerate(splits):
                    with _span("selector.fold_fit", fold=f, in_fold_dag=True):
                        dag_copy = [[copy.deepcopy(s) for s in layer]
                                    for layer in in_fold_dag]
                        _, fitted_dag = fit_dag(batch.take_rows(tr_idx),
                                                dag_copy)
                        full = apply_dag(batch, fitted_dag)
                    yield _col_values(full), [(tr_idx, va_idx)]
            else:
                yield _col_values(batch), splits

        import jax
        import jax.numpy as jnp

        from .sparse.matrix import SparseMatrix

        def drain_deferred():
            """Pull every pending device-scalar metric in one stacked
            transfer (falling back to per-metric pulls on failure).  Called
            at the end of the grid, and before each sweep-checkpoint flush —
            a flushed family's metric values must be real numbers, not the
            NaN placeholders the batched pull would patch later."""
            if not deferred:
                return
            try:
                vals = np.asarray(jnp.stack([m for m, _ in deferred]))
            except Exception as e:  # noqa: BLE001 — candidate robustness: one
                # bad candidate's runtime failure must not kill the whole
                # grid; fall back to per-metric pulls (failed ones stay NaN)
                record_failure("validator", "degraded", e,
                               point="selector.metric_pull",
                               fallback="per-metric pulls")
                vals = []
                for m, _ in deferred:
                    try:
                        vals.append(float(m))
                    except Exception as e2:  # noqa: BLE001
                        record_failure("validator", "skipped", e2,
                                       point="selector.metric_pull")
                        vals.append(float("nan"))
            for v, (lst, i) in zip(vals, (slot for _, slot in deferred)):
                lst[i] = float(v)
            deferred.clear()

        def checkpoint_family(ci, cand, fitted_grid):
            """Persist one completed candidate family into the ambient sweep
            checkpoint (atomic flush).  A checkpoint-write failure degrades —
            the sweep's correctness never depends on its durability."""
            entry = []
            for gi in range(len(cand.grid)):
                r = results.get((cand.model_name, ci * 10000 + gi))
                if r is not None:
                    entry.append({"params": r.params,
                                  "metricValues": r.metric_values,
                                  "racedOut": r.raced_out})
            try:
                sweep_cp.record_candidate(
                    sweep_sigs[ci], cand.model_name, ci, entry,
                    fitted_grid=fitted_grid
                    if isinstance(fitted_grid, list) else None)
                sweep_cp.flush()
                from .obsv import BOARD
                BOARD.publish(lastCheckpointFamily=cand.model_name)
            except Exception as e:  # noqa: BLE001
                record_failure(cand.model_name, "degraded", e,
                               point="checkpoint.save",
                               fallback="sweep continues unpersisted")

        # reuse the label column's own buffer so the weakref-keyed transfer
        # cache shares ONE host→device shipment with SanityChecker/evaluate
        y32 = np.asarray(batch[label].values, dtype=np.float32)
        # shape of the fold-weight mask used for the batched fits — the final
        # refit reuses it to hit the SAME compiled executable (shape-keyed)
        self.last_fit_shape = None if in_fold_dag else (len(splits), len(y32))
        self.family_fit_meta = {}
        if not live:
            # fully-replayed sweep: no grid executable was compiled this
            # process, so the winner refit must take the plain fit path
            self.last_fit_shape = None
            self.last_mesh = None
        from .columns import to_device_f32
        # zero-weight row padding (mesh divisibility quantum, ladder rungs)
        # is exact only for families that declare it — one non-exact family
        # in the grid keeps the whole shared matrix unpadded
        pad_exact_all = all(getattr(c.estimator, "weighted_pad_exact", False)
                            for c in candidates)
        for X, fsplits in fold_groups():
            is_sparse = isinstance(X, SparseMatrix)
            N = X.shape[0]
            # one device data plane (ISSUE 19): sparse matrices shard over
            # the mesh 'data' axis like dense ones — entries sort by row,
            # partition at device row boundaries, pad to a common per-device
            # nnz rung (DeviceTable).  Global row_ids let GSPMD insert the
            # collectives; the segment-sum fitters tolerate the zero pads
            # exactly (value 0.0 addends at an in-range row).
            mesh = self._maybe_mesh(N, pad=pad_exact_all)
            self.last_mesh = mesh
            if (mesh is None and not pad_exact_all
                    and self._maybe_mesh(N, pad=True) is not None):
                # honest degrade: the mesh WAS viable (pad-divisible) but a
                # mixed grid (some family not weighted_pad_exact) pinned the
                # matrix unpadded and indivisible — record it so bench aux
                # and operators see single-device as a degrade, not a choice
                record_failure(
                    "sweep", "degraded",
                    RuntimeError(
                        f"N={N} indivisible and grid mixes non-pad-exact "
                        f"families: sweep falls back to single device"),
                    point="selector.mesh", fallback="single_device")
                from .telemetry import REGISTRY as _REG
                _REG.counter("selector.mesh_degraded").inc()
            from .parallel import (data_axis_size, data_sharding,
                                   pad_rows_for, stream_to_device)
            from .parallel import memory as _mem
            _plan_chunk = None   # preflight-chosen streaming chunk bytes
            N_fit = N
            if mesh is not None:
                # multi-device: row-shard the matrix over the mesh 'data' axis
                # and let GSPMD insert the collectives inside every batched
                # fit/metric program (SURVEY §2.6 P1/P3 on the REAL path).
                # Row count pads up to the device-divisible quantum — and,
                # with the compile cache on, up to the fit-shape ladder rung —
                # with zero-weight rows; one padded matrix serves every
                # family (all are weighted_pad_exact whenever N_fit > N).
                extent = data_axis_size(mesh)
                N_fit = N + pad_rows_for(N, mesh)
                if _fit_padding_enabled() and pad_exact_all:
                    rung = _fit_pad_rows(N)
                    N_fit = max(N_fit, -(-rung // extent) * extent)
                if N_fit > N and not pad_exact_all:
                    N_fit = N   # divisible N, mixed families: no ladder pad
                if _mem.memory_governor_enabled():
                    # preflight (ISSUE 15): estimate the padded-rung ×
                    # dtype × grid-width × fold-panel footprint against the
                    # per-device budget and choose chunk bytes (and grid
                    # partitioning, read back by the fit bodies) BEFORE the
                    # first transfer — the 11M-row regime stops discovering
                    # OOM by dying in batched_device_put
                    plan = _mem.plan_sweep_memory(
                        rows=N_fit,
                        cols=(int(X.shape[1])
                              if is_sparse or getattr(X, "ndim", 1) == 2
                              else 1),
                        folds=len(fsplits),
                        grid_width=max((len(c.grid) for c in candidates),
                                       default=1),
                        devices=int(mesh.devices.size),
                        nnz=int(X.nnz) if is_sparse else None)
                    _plan_chunk = plan.chunk_bytes
                if is_sparse:
                    # COO entries stream by nnz range under the same chunk
                    # budget (DeviceTable dispatch inside stream_to_device);
                    # empty pad rows own no entries, so the nnz-rung pads are
                    # the only on-device synthesis
                    X = stream_to_device(X, mesh, pad_to=N_fit,
                                         chunk_bytes=_plan_chunk)
                elif isinstance(X, jax.Array):
                    # already device-resident (upstream DAG output): pad on
                    # device, then lay out over the mesh in one shot
                    Xj = X if X.dtype == jnp.float32 else X.astype(
                        jnp.float32)
                    if N_fit > N:
                        Xj = jnp.pad(Xj, ((0, N_fit - N), (0, 0)))
                    X = jax.device_put(Xj, data_sharding(mesh, 2))
                else:
                    # chunked host→device streaming: assemble each device's
                    # row shard from bounded host slices so peak staging is
                    # O(TRANSMOGRIFAI_DEVICE_CHUNK_BYTES), not O(dataset) —
                    # the one-shot device_put staged the whole matrix
                    # (BENCH_11M_ATTEMPTS_r4 hard faults)
                    X = stream_to_device(np.asarray(X, dtype=np.float32),
                                         mesh, pad_to=N_fit,
                                         chunk_bytes=_plan_chunk)
                if N_fit > N and not is_sparse:
                    # tree families quantile-bin over the true rows only —
                    # keeps padded split points identical to unpadded ones
                    # (sparse grids are linear-only: no binning to protect)
                    from .models.trees import register_real_rows
                    register_real_rows(X, N)
            elif not isinstance(X, jax.Array) and not is_sparse:
                # ONE host→device transfer shared by every candidate family —
                # the host link is the scarce resource on tunneled TPUs
                X = to_device_f32(X)
            is_dev = isinstance(X, jax.Array) or is_sparse
            y_dev = None
            if is_dev:
                # exact wire (bf16 only when verified lossless), shared with
                # every other consumer of the same label buffer
                y_dev = (stream_to_device(y32, mesh, pad_to=N_fit,
                                          chunk_bytes=_plan_chunk)
                         if mesh is not None else
                         to_device_f32(y32, exact=True))
            X_host = None if is_dev else X   # lazy d2h only if a fallback needs it
            va_slices = [va for _, va in fsplits]
            va_masks_dev = []
            assign = np.full(N_fit, _NO_FOLD, np.uint8)
            if N_fit > N:
                assign[N:] = _PAD_FOLD   # pad rows join NO fold, ever
            for f, (_, va_idx) in enumerate(fsplits):
                assign[va_idx] = f
            # dense per-fold weight rows only materialize when a splitter
            # may modify them (or the host path needs them below)
            W_rows = []
            neutral = splitter is None or (
                type(splitter).validation_prepare_weights
                is Splitter.validation_prepare_weights)
            if not neutral or not (is_dev and len(fsplits) < _PAD_FOLD):
                neutral = True
                for f, (tr_idx, _) in enumerate(fsplits):
                    w = np.zeros(N, np.float32)
                    w[tr_idx] = 1.0
                    if splitter is not None:
                        w2 = splitter.validation_prepare_weights(y_all, w)
                        neutral = neutral and w2 is w
                        w = w2
                    W_rows.append(w)
            if is_dev and neutral and len(fsplits) < _PAD_FOLD:
                # fold masks from ONE [N] uint8 assignment shipped over the
                # link — 1 byte/row instead of (folds+1)×4 bytes/row of
                # train + validation masks.  On the mesh the assignment is
                # row-sharded first so the [F, N] masks materialize directly
                # with the fit programs' expected sharding.
                aj = jnp.asarray(assign)
                if mesh is not None:
                    aj = jax.device_put(aj, data_sharding(mesh, 1))
                Wd, VAd = _fold_masks_from_assignment(aj, len(fsplits))
                W = Wd
                va_masks_dev = [VAd[f] for f in range(len(fsplits))]
            else:
                W = np.stack(W_rows)
                if is_dev:
                    for va_idx in va_slices:
                        vm = np.zeros(N, np.float32)
                        vm[va_idx] = 1.0
                        if mesh is not None:
                            # pad tail streams in as zeros — never validated
                            vmj = stream_to_device(vm, mesh, pad_to=N_fit,
                                                   chunk_bytes=_plan_chunk)
                        else:
                            vmj = to_device_f32(vm)  # 0/1 mask: bf16 exact
                        va_masks_dev.append(vmj)
                if mesh is not None:
                    W = stream_to_device(W, mesh, row_axis=1, pad_to=N_fit,
                                         chunk_bytes=_plan_chunk)
                else:
                    # one shared transfer; family fits see a no-op conversion.
                    # exact=True: bf16 wire only when verified lossless (0/1
                    # fold masks; balancer keep/drop weights) — custom
                    # splitters may emit arbitrary weights, which go exact f32
                    W = to_device_f32(W, exact=True)
            # fit-shape canonicalization (ISSUE 4 compile reuse): one shared
            # zero-weight-row-padded copy of (X, y) serves every pad-exact
            # family, so nearby row counts land on the same ladder rung and
            # hit the persistent compile cache.  The mesh path already folded
            # its ladder rung into N_fit during streaming, so this separate
            # padded copy exists only off-mesh.
            pad_rows = 0
            X_pad = y_pad = None
            if (_fit_padding_enabled() and mesh is None
                    and any(getattr(c.estimator, "weighted_pad_exact", False)
                            for c in candidates)):
                pad_rows = _fit_pad_rows(N) - N
            if pad_rows:
                if is_sparse:
                    # empty rows own no COO entries and carry weight 0 —
                    # exact for the weight-normalized sparse fitters
                    X_pad = X.pad_rows(N + pad_rows)
                    y_pad = jnp.pad(y_dev, (0, pad_rows))
                elif is_dev:
                    X_pad = jnp.pad(X, ((0, pad_rows), (0, 0)))
                    y_pad = jnp.pad(y_dev, (0, pad_rows))
                else:
                    X_pad = np.pad(X, ((0, pad_rows), (0, 0)))
                    y_pad = np.pad(y32, (0, pad_rows))
                if not is_sparse:
                    # tree families quantile-bin over the true rows only —
                    # keeps padded split points identical to unpadded ones
                    from .models.trees import register_real_rows
                    register_real_rows(X_pad, N)

            def _pad_weight_cols(Wblk):
                if isinstance(Wblk, np.ndarray):
                    return np.pad(Wblk, ((0, 0), (0, pad_rows)))
                return jnp.pad(Wblk, ((0, 0), (0, pad_rows)))

            # concurrent pre-trace (aot.py): lower+compile each supporting
            # family's grid programs on a background thread NOW, so by the
            # time the fit pool below reaches them the persistent compile
            # cache already holds the executables and
            # new_compiles_during_train collapses into overlapped wall time.
            # Compile-only — sweep winners are bitwise unaffected.
            from .aot import pretrace_enabled, pretrace_submit
            if pretrace_enabled():
                for ci, cand in enumerate(candidates):
                    if (ci in replayed or not getattr(
                            cand.estimator, "supports_pretrace", False)):
                        continue
                    use_pad = bool(pad_rows) and getattr(
                        cand.estimator, "weighted_pad_exact", False)
                    Xf = X_pad if use_pad else X
                    yf = (y_pad if use_pad
                          else y_dev if y_dev is not None else y32)

                    def _submit(Wblk, grid, est=cand.estimator, Xf=Xf,
                                yf=yf, name=cand.model_name):
                        Wf = _pad_weight_cols(Wblk) if use_pad else Wblk
                        pretrace_submit(
                            name, lambda: est.pretrace_arrays_grid(
                                Xf, yf, Wf, grid))
                    if raced_flags[ci]:
                        # round A (full grid, fold 0) is certain; round B's
                        # survivor subset is data-dependent — pre-trace a
                        # same-sized prefix as a best-effort shape/static
                        # match (a miss just forfeits the overlap)
                        _submit(W[:1], cand.grid)
                        _submit(W, cand.grid[:_survivor_count(
                            len(cand.grid))])
                    else:
                        _submit(W, cand.grid)

            # control-plane progress: candidate-fit boundaries feed the
            # /statusz board (current family + grid point) and the per-unit
            # EWMA behind its ETA.  _fits_left is per round (A, then B).
            _fits_left = [0]

            def fit_candidate(cand, Wblk, grid):
                # per-candidate trace span: worker threads have no span of
                # their own, so this parents under the orchestrating
                # selector.sweep span even through the thread pool
                import time as _time

                from .obsv import BOARD
                from .telemetry import span as _span
                BOARD.publish(candidate=cand.model_name,
                              candidateGrid=len(grid),
                              candidateFolds=int(len(Wblk)))
                t0 = _time.perf_counter()
                with _span("selector.candidate_fit", model=cand.model_name,
                           grid=len(grid), folds=int(len(Wblk))):
                    out = _fit_candidate_body(cand, Wblk, grid)
                _fits_left[0] = max(0, _fits_left[0] - 1)
                BOARD.note_unit(_time.perf_counter() - t0,
                                remaining_units=_fits_left[0])
                return out

            def _fit_candidate_body(cand, Wblk, grid):
                from .parallel import memory as _memq
                from .telemetry import span as _span
                use_pad = bool(pad_rows) and getattr(
                    cand.estimator, "weighted_pad_exact", False)
                Xf = X_pad if use_pad else X
                yf = (y_pad if use_pad
                      else y_dev if y_dev is not None else y32)
                Wf = _pad_weight_cols(Wblk) if use_pad else Wblk
                try:
                    maybe_inject("selector.candidate_fit", key=cand.model_name)
                    # chaos seam for mid-sweep device loss during a fit; the
                    # key carries the sweep attempt so the post-recovery
                    # retry is not re-killed by a sticky injector decision
                    maybe_inject(
                        "supervisor.device_loss",
                        key=f"{cand.model_name}:fit:"
                            f"a{getattr(self, '_sweep_attempt', 0)}")
                    # chaos seam for a mid-sweep allocator OOM; keyed by the
                    # memory-ladder attempt for the same reason — the
                    # shrunken retry must not be re-killed
                    maybe_inject(
                        "memory.device_oom",
                        key=f"{cand.model_name}:fit:"
                            f"o{getattr(self, '_oom_attempt', 0)}")
                    if _memq.per_candidate_fallback():
                        # memory ladder's last rung: no batched grid program
                        # at all — the per-(fold, point) working set is the
                        # smallest the sweep can make
                        raise MemoryError(
                            "memory ladder: per-candidate fallback")
                    parts = _memq.grid_partitions()
                    if parts > 1 and len(grid) > 1:
                        # memory ladder rung 2+ (or the preflight plan):
                        # split the batched (fold × grid) program into grid
                        # sub-batches so each program's lane working set
                        # shrinks with the partition count
                        sub = -(-len(grid) // min(parts, len(grid)))
                        outs = [cand.estimator.fit_arrays_grid(
                                    Xf, yf, Wf, grid[i:i + sub])
                                for i in range(0, len(grid), sub)]
                        out = [[fit for o in outs for fit in o[f]]
                               for f in range(len(outs[0]))]
                    else:
                        out = cand.estimator.fit_arrays_grid(Xf, yf, Wf,
                                                             grid)
                    self.family_fit_meta[cand.model_name] = {
                        "folds": len(out), "rows": int(Xf.shape[0]),
                        "real_rows": int(N), "lanes": len(grid),
                        # ladder copy OR mesh-streamed quantum/rung padding
                        "padded": int(Xf.shape[0]) > int(N)}
                    return out
                except Exception as e:  # noqa: BLE001
                    # a lost device is NOT a bad candidate: per-point refits
                    # on a dead mesh would fail K×|grid| more times — let the
                    # sweep-level recovery rebuild the surviving mesh instead
                    from .parallel.supervisor import is_device_loss
                    if is_device_loss(e):
                        raise
                    # allocator exhaustion is not a bad candidate either —
                    # unless the ladder already reached its last rung, where
                    # per-point refits ARE the recovery
                    if (_memq.is_memory_exhaustion(e)
                            and not _memq.per_candidate_fallback()):
                        raise
                    # batched fit failed as a block — retry per point so one
                    # bad candidate can't take down the family (≙ Try-wrapped
                    # fits in OpValidator.getSummary).  Per-point refits run
                    # unpadded: exactness beats executable reuse on a path
                    # that is already degraded.
                    record_failure(cand.model_name, "degraded", e,
                                   point="selector.candidate_fit",
                                   fallback="per-point refits")
                    self.family_fit_meta.pop(cand.model_name, None)
                    fitted_grid = []
                    for f in range(len(Wblk)):
                        with _span("selector.fold_fit",
                                   model=cand.model_name, fold=f,
                                   degraded=True):
                            row = []
                            for gi, params in enumerate(grid):
                                try:
                                    maybe_inject("selector.candidate_fit",
                                                 key=cand.model_name)
                                    est = copy.deepcopy(cand.estimator)
                                    for k, v in params.items():
                                        est.set(k, v)
                                    # mesh path: X carries streamed pad rows,
                                    # so pair it with the matching padded
                                    # sharded label/weight vectors
                                    yfb = y_dev if mesh is not None else y32
                                    row.append(est.fit_arrays(
                                        X, yfb, sample_weight=Wblk[f]))
                                except Exception as e2:  # noqa: BLE001
                                    if is_device_loss(e2):
                                        raise
                                    record_failure(
                                        cand.model_name, "skipped", e2,
                                        point="selector.candidate_fit",
                                        fold=f, grid_index=gi)
                                    row.append(None)
                        fitted_grid.append(row)
                    return fitted_grid

            # candidate families fit concurrently on a thread pool (≙ the
            # reference's Futures fan-out, OpValidator.scala:320-349 +
            # `parallelism` :106).  Device execution serializes on the TPU
            # stream; the win is overlapping the XLA *compiles* of the
            # per-family batched programs, which dominate first-run wall.
            # At very large N the families' HBM working sets no longer fit
            # side by side (each TREE family budgets ~6 GiB of one-hot
            # space) — fit sequentially so peak = max, not sum.  Grids with
            # no HBM-heavy family keep the compile-overlap pool at any N.
            import os as _os

            def fit_or_skip(icand):
                """Candidate boundary: replay beats fit, and a requested
                graceful stop (signal or injected preemption) wins over
                starting new work."""
                ci, cand = icand
                if ci in replayed:
                    return _REPLAYED
                if shutdown_requested(key=cand.model_name):
                    preempted.append(cand.model_name)
                    return _PREEMPTED
                if raced_flags[ci]:
                    # successive-halving round A: full grid, fold 0 only
                    return fit_candidate(cand, W[:1], cand.grid)
                return fit_candidate(cand, W, cand.grid)

            serial_rows = int(_os.environ.get(
                "TRANSMOGRIFAI_SERIAL_FIT_ROWS", 4_000_000))
            n_workers = min(self.parallelism, len(candidates))
            if N >= serial_rows and any(
                    getattr(c.estimator, "hbm_heavy", False)
                    for c in candidates):
                n_workers = 1
            indexed = list(enumerate(candidates))
            _fits_left[0] = len(indexed)
            from .obsv import BOARD
            BOARD.publish(round="A", fitsQueued=len(indexed))
            if n_workers > 1:
                from concurrent.futures import ThreadPoolExecutor
                with ThreadPoolExecutor(max_workers=n_workers) as pool:
                    fitted_grids = list(pool.map(fit_or_skip, indexed))
            else:
                fitted_grids = [fit_or_skip(ic) for ic in indexed]

            va_cache: Dict[int, Tuple[np.ndarray, np.ndarray]] = {}

            def va_slice(f, va_idx):
                """Pulled validation slice, cached per FOLD so every
                fallback candidate shares one transfer."""
                if f not in va_cache:
                    nonlocal X_host
                    if is_sparse:
                        # the slice STAYS sparse: sparse-capable models
                        # consume the COO stream in predict_arrays; models
                        # without a sparse path fail loudly (__array__
                        # raises) and the resilience layer skips them
                        xv = X.take_rows(np.asarray(va_idx))
                    elif is_dev:
                        # gather ONLY the validation slice on device, then
                        # pull — the full matrix is folds-times bigger and
                        # the link is the bottleneck.  Cast bf16-stored
                        # matrices to f32 on device first: numpy kernels on
                        # ml_dtypes bf16 are limited/slow on host
                        xv = np.asarray(jnp.take(
                            X, jnp.asarray(va_idx), axis=0
                        ).astype(jnp.float32))
                    else:
                        if X_host is None:
                            X_host = np.asarray(X)
                        xv = X_host[va_idx]
                    va_cache[f] = (xv, y32[va_idx])
                return va_cache[f]

            def score_block(cand, ci, fitted_grid, fold_offset, n_folds,
                            rec):
                """Score a fitted (n_folds × grid) block against validation
                folds [fold_offset, fold_offset + n_folds) — batched fast
                path first, device/host per-candidate fallback otherwise.
                ``rec`` lets racing remap a survivor sub-grid's local
                indices back to the family's full grid."""
                BOARD.publish(scoring=cand.model_name,
                              foldOffset=fold_offset, foldCount=n_folds)
                # chaos seam: a device lost between fitting and scoring —
                # fires AFTER earlier families checkpointed, so the recovery
                # sweep demonstrably replays them from the SweepCheckpoint
                maybe_inject(
                    "supervisor.device_loss",
                    key=f"{cand.model_name}:score:"
                        f"a{getattr(self, '_sweep_attempt', 0)}")
                maybe_inject(
                    "memory.device_oom",
                    key=f"{cand.model_name}:score:"
                        f"o{getattr(self, '_oom_attempt', 0)}")
                masks = va_masks_dev[fold_offset:fold_offset + n_folds]
                if (is_dev and self._record_grid_metrics_batched(
                        cand, ci, fitted_grid, X, y_dev, masks, rec)):
                    return
                for f_local in range(n_folds):
                    f = fold_offset + f_local
                    va_idx = va_slices[f]
                    for gi, params in enumerate(cand.grid):
                        fitted = fitted_grid[f_local][gi]
                        if fitted is None:
                            rec(cand, ci, gi, params, float("nan"))
                            continue
                        metric = None
                        if is_dev:
                            metric = device_metric(cand, params, fitted,
                                                   X, y_dev,
                                                   va_masks_dev[f])
                        if metric is None:
                            metric = host_metric(cand, params, fitted,
                                                 *va_slice(f, va_idx))
                        rec(cand, ci, gi, params, metric)

            # round A: raced families score their fold-0 screen; unraced
            # families score (and checkpoint) their full CV block exactly
            # as an unraced sweep would
            for ci, cand in enumerate(candidates):
                fitted_grid = fitted_grids[ci]
                if fitted_grid is _REPLAYED or fitted_grid is _PREEMPTED:
                    continue
                if raced_flags[ci]:
                    score_block(cand, ci, fitted_grid, 0, 1, record)
                    continue
                score_block(cand, ci, fitted_grid, 0, len(fsplits), record)
                if sweep_cp is not None:
                    drain_deferred()
                    checkpoint_family(ci, cand, fitted_grid)

            # round B: rank each raced family's fold-0 screen in the
            # evaluator's direction, prune past the survivor floor, then fit
            # + score ONLY the survivors on the remaining folds — the
            # (folds-1) × (grid - survivors) fits never run
            race_live = [ci for ci in range(len(candidates))
                         if raced_flags[ci]
                         and fitted_grids[ci] is not _REPLAYED
                         and fitted_grids[ci] is not _PREEMPTED]
            if race_live:
                drain_deferred()   # ranking needs numbers, not deferred slots
                sign = 1.0 if self.evaluator.is_larger_better else -1.0
                _raced_out: Dict[str, int] = {}

                def prune(ci, cand):
                    G = len(cand.grid)
                    S = _survivor_count(G)

                    def keyf(gi):
                        r = results.get((cand.model_name, ci * 10000 + gi))
                        v = (r.metric_values[0]
                             if r and r.metric_values else float("nan"))
                        return sign * v if np.isfinite(v) else -np.inf

                    # deterministic: ties and NaNs break by grid position
                    order = sorted(range(G), key=lambda gi: (-keyf(gi), gi))
                    for gi in order[S:]:
                        r = results.get((cand.model_name, ci * 10000 + gi))
                        if r is not None:
                            r.raced_out = True
                    from .telemetry import event as _event
                    _event("selector.racing.prune", model=cand.model_name,
                           grid=G, survivors=S, pruned=G - S)
                    _raced_out[cand.model_name] = G - S
                    BOARD.publish(racedOut=dict(_raced_out))
                    return sorted(order[:S])

                survivors_by_ci = {ci: prune(ci, candidates[ci])
                                   for ci in race_live}

                def sub_candidate(ci):
                    cand = candidates[ci]
                    return ModelCandidate(
                        cand.estimator,
                        [dict(cand.grid[g]) for g in survivors_by_ci[ci]],
                        cand.model_name)

                def fit_survivors(ci):
                    cand = candidates[ci]
                    if shutdown_requested(key=cand.model_name):
                        preempted.append(cand.model_name)
                        return _PREEMPTED
                    sub = sub_candidate(ci)
                    return fit_candidate(sub, W[1:], sub.grid)

                _fits_left[0] = len(race_live)
                BOARD.publish(round="B", fitsQueued=len(race_live))
                if n_workers > 1 and len(race_live) > 1:
                    from concurrent.futures import ThreadPoolExecutor
                    with ThreadPoolExecutor(
                            max_workers=min(n_workers,
                                            len(race_live))) as pool:
                        fitted_b = list(pool.map(fit_survivors, race_live))
                else:
                    fitted_b = [fit_survivors(ci) for ci in race_live]

                from .profiling import record_racing
                rest = len(fsplits) - 1
                for ci, fb in zip(race_live, fitted_b):
                    cand = candidates[ci]
                    if fb is _PREEMPTED:
                        continue
                    survivors = survivors_by_ci[ci]

                    def rec(_c, _ci, gi_local, params, metric,
                            _map=survivors, _cand=cand, _i=ci):
                        record(_cand, _i, _map[gi_local], params, metric)

                    score_block(sub_candidate(ci), ci, fb, 1, rest, rec)
                    record_racing(rest * (len(cand.grid) - len(survivors)),
                                  len(cand.grid) - len(survivors))
                    if sweep_cp is not None:
                        drain_deferred()
                        checkpoint_family(ci, cand, None)

        if preempted:
            # graceful stop honored at a candidate boundary: everything
            # completed so far is drained + flushed (per family, above);
            # hand the caller the resume point instead of dying mid-write
            drain_deferred()
            raise TrainingPreempted(
                "selector sweep stopped before candidate(s) "
                + ", ".join(sorted(set(preempted))),
                resume_from=sweep_cp.path if sweep_cp is not None else None)

        drain_deferred()   # ONE pull for every device-scalar metric left

        all_results = list(results.values())
        sign = 1.0 if self.evaluator.is_larger_better else -1.0
        # raced-out points carry a fold-0 screen mean only; comparing that
        # against survivors' full-k-fold means would be apples-to-oranges,
        # so they are excluded from winner selection (kept in all_results
        # for the summary). If racing somehow pruned everything that
        # finished, fall back to the full list rather than fail the sweep.
        scored = [(sign * r.mean_metric, r) for r in all_results
                  if np.isfinite(r.mean_metric) and not r.raced_out]
        if not scored:
            scored = [(sign * r.mean_metric, r) for r in all_results
                      if np.isfinite(r.mean_metric)]
        if not scored:
            # aggregate error with per-candidate causes from the failure log
            # — "nothing survived" alone is undebuggable at 3am
            causes: Dict[str, str] = {}
            for ev in active_failure_log().events:
                if ev.point.startswith("selector.") and ev.cause:
                    causes.setdefault(ev.stage, ev.cause)
            for cand in candidates:
                causes.setdefault(cand.model_name,
                                  "no finite validation metric")
            raise AllCandidatesFailed(
                "all model candidates failed validation", causes)
        best_score, best_res = max(scored, key=lambda t: t[0])
        best_cand = candidates[best_res.candidate_index]
        import copy as _c
        best_est = _c.deepcopy(best_cand.estimator)
        for k, v in best_res.params.items():
            best_est.set(k, v)
        return ValidationResult(
            best=ModelCandidate(best_est, [dict(best_res.params)], best_res.model_name),
            best_params=dict(best_res.params),
            best_metric=best_res.mean_metric,
            all_results=all_results,
            validation_type=self.validation_type,
            metric_name=self.evaluator.default_metric,
            is_larger_better=self.evaluator.is_larger_better)


class OpCrossValidation(OpValidator):
    """k-fold CV (≙ OpCrossValidation.scala:42); default 3 folds."""

    validation_type = "CrossValidation"

    def __init__(self, num_folds: int = 3, evaluator: Optional[OpEvaluatorBase] = None,
                 seed: int = 42, stratify: bool = False, parallelism: int = 8,
                 **kw):
        super().__init__(evaluator, seed, stratify, parallelism, **kw)
        self.num_folds = int(num_folds)

    def splits(self, y: np.ndarray):
        n = len(y)
        rng = np.random.default_rng(self.seed)
        perm = self._stratified_perm(y, rng) if self.stratify else rng.permutation(n)
        folds = np.array_split(perm, self.num_folds)
        out = []
        for i in range(self.num_folds):
            va = folds[i]
            tr = np.concatenate([folds[j] for j in range(self.num_folds) if j != i])
            out.append((tr, va))
        return out


class OpTrainValidationSplit(OpValidator):
    """single split (≙ OpTrainValidationSplit); default 75/25."""

    validation_type = "TrainValidationSplit"

    def __init__(self, train_ratio: float = 0.75,
                 evaluator: Optional[OpEvaluatorBase] = None, seed: int = 42,
                 stratify: bool = False, parallelism: int = 8, **kw):
        super().__init__(evaluator, seed, stratify, parallelism, **kw)
        self.train_ratio = float(train_ratio)

    def splits(self, y: np.ndarray):
        n = len(y)
        rng = np.random.default_rng(self.seed)
        perm = self._stratified_perm(y, rng) if self.stratify else rng.permutation(n)
        n_tr = int(round(n * self.train_ratio))
        return [(perm[:n_tr], perm[n_tr:])]
