"""OpParams — JSON-loadable run configuration (reference:
features/src/main/scala/com/salesforce/op/OpParams.scala:81, ReaderParams;
per-stage injection OpWorkflow.setStageParameters, OpWorkflow.scala:178-199).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, Dict, Optional


@dataclass
class ReaderParams:
    """≙ ReaderParams: per-reader path + custom params."""
    path: Optional[str] = None
    partitions: Optional[int] = None
    custom: Dict[str, Any] = field(default_factory=dict)


@dataclass
class OpParams:
    """≙ OpParams.scala:81."""

    stage_params: Dict[str, Dict[str, Any]] = field(default_factory=dict)
    reader_params: Dict[str, ReaderParams] = field(default_factory=dict)
    model_location: Optional[str] = None
    write_location: Optional[str] = None
    metrics_location: Optional[str] = None
    checkpoint_location: Optional[str] = None   # sweep + streaming progress
    batch_size: Optional[int] = None
    custom_tag_name: Optional[str] = None
    custom_params: Dict[str, Any] = field(default_factory=dict)
    collect_metrics: bool = False
    # online-serving knobs (run-type "serve"): host, port, maxBatch,
    # queueBound, requestDeadlineS, reloadPollS, workers (>1 runs the
    # SO_REUSEPORT pool with a parent supervisor; adminPort for its
    # aggregated /metrics), wireFormat ("auto" accepts the packed columnar
    # body per request Content-Type, "json" rejects it with 415),
    # lingerMs (deprecated, ignored by the continuous batcher), plus the
    # overload control plane (serving.overload.OverloadConfig.from_params):
    # latencyTargetMs, adaptiveLimit, minLimit, queueDeadlineMs,
    # brownoutHigh, brownoutLow, breakerWindow, breakerFailures,
    # breakerRate, breakerMinCalls, breakerResetS, halfOpenProbes,
    # reloadBreakerFailures, reloadBreakerResetS.
    # Multi-tenant serving: modelRoot (a directory of per-tenant bundles;
    # replaces --model-location and routes /v1/score/<tenant> /
    # X-Model-Id / modelId through per-tenant bulkheaded engines),
    # tenantMaxActive (LRU cap on loaded tenant engines),
    # tenantMemoryBudgetBytes (device-memory budget the active tenant
    # set is charged against; default device_memory_budget())
    serving: Dict[str, Any] = field(default_factory=dict)
    # sweep-racing knobs applied to every ModelSelector validator: enabled,
    # eta, minSurvivors (see DefaultSelectorParams.RACING*)
    racing: Dict[str, Any] = field(default_factory=dict)
    # telemetry knobs: traceDir (where chrome-trace + telemetry.json land),
    # enabled (default: true when traceDir is set), summaryTopN,
    # traceparent (W3C `traceparent` header value — joins this run's spans
    # to the caller's distributed trace; defaults to the
    # TRANSMOGRIFAI_TRACEPARENT env var a supervising parent exported)
    telemetry: Dict[str, Any] = field(default_factory=dict)
    # lifecycle knobs (run-type "lifecycle"): policy, psiThreshold,
    # scorePsiThreshold, fillDeltaThreshold, minRows, intervalS,
    # minRetrainIntervalS, tolerance, warmStart, maxIterations,
    # batchesPerCheck, pollS, forceRetrain
    lifecycle: Dict[str, Any] = field(default_factory=dict)
    # AOT-executable knobs (aot.py): enabled (default true — set false or
    # pass --no-aot to save/load JIT-only bundles), ladderMax (largest
    # padded batch size exported at save time)
    aot: Dict[str, Any] = field(default_factory=dict)
    # compiled-program registry knobs (aot_registry.py): enabled (default
    # true — set false or pass --no-registry for pre-registry behavior),
    # root (--registry-root / TRANSMOGRIFAI_AOT_REGISTRY; defaults to
    # <checkpoint-location>/registry), capBytes
    # (TRANSMOGRIFAI_AOT_REGISTRY_CAP_BYTES eviction budget), keepMin
    # (TRANSMOGRIFAI_AOT_REGISTRY_KEEP_MIN entries never evicted),
    # cacheCapBytes (TRANSMOGRIFAI_COMPILE_CACHE_CAP_BYTES budget for the
    # persistent XLA compile cache)
    registry: Dict[str, Any] = field(default_factory=dict)
    # mesh-sharded sweep knobs (parallel/mesh.py env equivalents): enabled
    # (TRANSMOGRIFAI_TPU_MESH), modelWidth (TRANSMOGRIFAI_TPU_MESH_MODEL),
    # chunkBytes (TRANSMOGRIFAI_DEVICE_CHUNK_BYTES), minRows
    # (TRANSMOGRIFAI_TPU_MESH_MIN_ROWS)
    mesh: Dict[str, Any] = field(default_factory=dict)
    # device-runtime supervisor knobs (parallel/supervisor.py env
    # equivalents): enabled (TRANSMOGRIFAI_SUPERVISOR; --no-supervisor),
    # probeTimeoutS (TRANSMOGRIFAI_PROBE_TIMEOUT_S), probeBackoffs
    # (TRANSMOGRIFAI_PROBE_BACKOFFS), chunkDeadlineS
    # (TRANSMOGRIFAI_CHUNK_DEADLINE_S), sweepRecoveries
    # (TRANSMOGRIFAI_SWEEP_RECOVERIES), outageDir
    # (TRANSMOGRIFAI_OUTAGE_DIR), heartbeatS (TRANSMOGRIFAI_HEARTBEAT_S)
    supervisor: Dict[str, Any] = field(default_factory=dict)
    # host-group (multi-process training) knobs (parallel/hostgroup.py env
    # equivalents): hosts (--hosts N launcher fan-out), beatIntervalS
    # (TRANSMOGRIFAI_HOSTGROUP_BEAT_S), livenessTimeoutS
    # (TRANSMOGRIFAI_HOSTGROUP_LIVENESS_S), barrierTimeoutS
    # (TRANSMOGRIFAI_HOSTGROUP_BARRIER_S), initTimeoutS
    # (TRANSMOGRIFAI_HOSTGROUP_INIT_S), distributed
    # (TRANSMOGRIFAI_HOSTGROUP_DISTRIBUTED — jax.distributed per rank),
    # maxRelaunches, bootTimeoutS, graceS, runDir (launcher-side)
    hostgroup: Dict[str, Any] = field(default_factory=dict)
    # memory-governance knobs (parallel/memory.py env equivalents): enabled
    # (TRANSMOGRIFAI_MEMORY_GOVERNOR; --no-memory-governor), deviceMemBytes
    # (TRANSMOGRIFAI_DEVICE_MEM_BYTES per-device budget override), headroom
    # (TRANSMOGRIFAI_MEMORY_HEADROOM XLA-temp factor), oomRecoveries
    # (TRANSMOGRIFAI_OOM_RECOVERIES shrink-ladder budget), hostSoftBytes /
    # hostHardBytes (TRANSMOGRIFAI_HOST_MEM_SOFT_BYTES / _HARD_BYTES RSS
    # watchdog watermarks), watchdogIntervalS (TRANSMOGRIFAI_RSS_WATCHDOG_S)
    memory: Dict[str, Any] = field(default_factory=dict)
    # data-quality firewall knobs (quality.py env equivalents): policy
    # (TRANSMOGRIFAI_QUALITY_POLICY: strict | coerce | quarantine | off;
    # --quality-policy), maxQuarantineFraction
    # (TRANSMOGRIFAI_MAX_QUARANTINE_FRACTION — training aborts with
    # DataQualityError past it), enabled (TRANSMOGRIFAI_QUALITY;
    # --no-quality)
    quality: Dict[str, Any] = field(default_factory=dict)
    # training control plane knobs (obsv.py env equivalents): port
    # (TRANSMOGRIFAI_OBS_PORT / --obs-port admin endpoint — /metrics,
    # /statusz, /traces; 0/unset = off, zero hot-path cost),
    # blackboxSpans (TRANSMOGRIFAI_BLACKBOX_SPANS flight-recorder ring
    # cap), blackboxPath (TRANSMOGRIFAI_BLACKBOX_PATH crash-dump
    # destination; defaults near the outage record)
    obs: Dict[str, Any] = field(default_factory=dict)

    @staticmethod
    def from_json(d: Dict[str, Any]) -> "OpParams":
        readers = {k: ReaderParams(path=v.get("path"),
                                   partitions=v.get("partitions"),
                                   custom=v.get("customParams") or {})
                   for k, v in (d.get("readerParams") or {}).items()}
        return OpParams(
            stage_params=d.get("stageParams") or {},
            reader_params=readers,
            model_location=d.get("modelLocation"),
            write_location=d.get("writeLocation"),
            metrics_location=d.get("metricsLocation"),
            checkpoint_location=d.get("checkpointLocation"),
            batch_size=d.get("batchSize"),
            custom_tag_name=d.get("customTagName"),
            custom_params=d.get("customParams") or {},
            collect_metrics=bool(d.get("collectMetrics", False)),
            serving=d.get("servingParams") or {},
            racing=d.get("racingParams") or {},
            telemetry=d.get("telemetryParams") or {},
            lifecycle=d.get("lifecycleParams") or {},
            aot=d.get("aotParams") or {},
            registry=d.get("registryParams") or {},
            mesh=d.get("meshParams") or {},
            supervisor=d.get("supervisorParams") or {},
            hostgroup=d.get("hostgroupParams") or {},
            memory=d.get("memoryParams") or {},
            quality=d.get("qualityParams") or {},
            obs=d.get("obsParams") or {})

    @staticmethod
    def load(path: str) -> "OpParams":
        with open(path) as fh:
            return OpParams.from_json(json.load(fh))

    def to_json(self) -> Dict[str, Any]:
        return {
            "stageParams": self.stage_params,
            "readerParams": {k: {"path": v.path, "partitions": v.partitions,
                                 "customParams": v.custom}
                             for k, v in self.reader_params.items()},
            "modelLocation": self.model_location,
            "writeLocation": self.write_location,
            "metricsLocation": self.metrics_location,
            "checkpointLocation": self.checkpoint_location,
            "batchSize": self.batch_size,
            "customTagName": self.custom_tag_name,
            "customParams": self.custom_params,
            "collectMetrics": self.collect_metrics,
            "servingParams": self.serving,
            "racingParams": self.racing,
            "telemetryParams": self.telemetry,
            "lifecycleParams": self.lifecycle,
            "aotParams": self.aot,
            "registryParams": self.registry,
            "meshParams": self.mesh,
            "supervisorParams": self.supervisor,
            "hostgroupParams": self.hostgroup,
            "memoryParams": self.memory,
            "qualityParams": self.quality,
            "obsParams": self.obs,
        }

    def apply_stage_params(self, stages) -> None:
        """≙ OpWorkflow.setStageParameters: match stage class simple name →
        stage.set(param, value)."""
        for st in stages:
            cls_name = type(st).__name__
            for match, params in self.stage_params.items():
                if cls_name == match or cls_name.startswith(match):
                    for k, v in params.items():
                        st.set(k, v)
