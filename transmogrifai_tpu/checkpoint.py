"""Crash-safe checkpointing — durability for the persistence and training
layers.

``resilience.py`` makes the execution layer survive failures *inside* a
process (retries, watchdogs, degradations); this module makes the system
survive the death of the process itself — the single most common failure on
preemptible TPU fleets.  Three pieces:

* **Atomic, versioned, checksummed bundles.**  ``atomic_bundle_write`` stages
  every file of a model bundle in a temp sibling directory, writes a
  ``MANIFEST.json`` with a format version and per-file SHA-256 digests,
  fsyncs, and atomically renames into place — a crash mid-save can never
  leave a torn bundle at the final path.  ``verify_bundle`` re-checks the
  digests and version on load, raising ``CorruptModelError`` /
  ``ModelVersionError`` naming the offending file; ``find_latest_valid``
  lets a loader pointed at a checkpoint *root* fall back to the newest
  bundle that still verifies.
* **Resumable selector sweeps.**  ``SweepCheckpoint`` persists completed
  (model × grid) candidate results (scores + fitted arrays, split the same
  way the stage ``save_extra`` machinery splits JSON from npz) after each
  candidate family finishes; a restarted ``train(resume_from=...)`` replays
  them and skips the already-evaluated candidates, reporting every
  resumption through the ambient ``FailureLog``.
* **Preemption-aware shutdown.**  ``preemption_guard`` installs SIGTERM /
  SIGINT handlers for the dynamic extent of ``train()`` and streaming
  scoring; the first signal requests a graceful stop which the sweep and
  micro-batch loops honor at the next candidate/batch boundary (flushing a
  final checkpoint + streaming offsets), the second raises.  The
  ``preemption`` injection point lets chaos tests trigger the same path
  without real signals.
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import signal
import threading
import time
import uuid
from contextlib import contextmanager
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from .resilience import InjectedFault, maybe_inject, record_failure

MANIFEST_NAME = "MANIFEST.json"
# version 2: manifests digest the whole tree recursively (relative POSIX
# paths as keys) and bundles may carry per-platform AOT executable
# subdirectories (aot-<platform>/, see aot.py) stamped under the manifest's
# "aot" entry.  Version-1 bundles remain fully readable — they simply load
# on the JIT path.
BUNDLE_FORMAT_VERSION = 2
_VERSION_DIR_PREFIX = "ckpt-"


# --------------------------------------------------------------------------
# errors
# --------------------------------------------------------------------------

class CheckpointError(RuntimeError):
    """Base of all checkpoint/bundle integrity errors."""


class CorruptModelError(CheckpointError):
    """A model bundle failed integrity verification.

    ``path`` is the bundle directory, ``file`` the offending file (or ""
    for whole-bundle problems), ``reason`` the specific failure."""

    def __init__(self, path: str, file: str = "", reason: str = ""):
        self.path = str(path)
        self.file = str(file)
        self.reason = str(reason)
        at = f"{self.path}/{self.file}" if self.file else self.path
        super().__init__(f"corrupt model bundle: {at}: "
                         f"{self.reason or 'integrity check failed'}")


class ModelVersionError(CheckpointError):
    """A bundle's format version is outside what this build can read."""

    def __init__(self, path: str, found: Any,
                 supported: int = BUNDLE_FORMAT_VERSION):
        self.path = str(path)
        self.found = found
        self.supported = supported
        super().__init__(
            f"model bundle {self.path}: format version {found!r} is not "
            f"readable by this build (supports 1..{supported}); "
            f"re-save the model with a matching version")


class TrainingPreempted(RuntimeError):
    """``train()`` stopped gracefully at a candidate boundary after a
    preemption signal (or injected preemption).  ``resume_from`` names the
    sweep checkpoint to pass back to ``train(resume_from=...)``."""

    def __init__(self, message: str, resume_from: Optional[str] = None):
        self.resume_from = resume_from
        self.failure_log = None   # attached by Workflow.train on the way out
        if resume_from:
            message = f"{message} (resume with resume_from={resume_from!r})"
        super().__init__(message)


# --------------------------------------------------------------------------
# digests + fsync
# --------------------------------------------------------------------------

def _sha256_file(path: str, chunk: int = 1 << 20) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as fh:
        while True:
            b = fh.read(chunk)
            if not b:
                break
            h.update(b)
    return h.hexdigest()


def _fsync_path(path: str) -> None:
    """fsync a file or directory; best-effort on platforms that refuse
    directory fds."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def write_json_atomic(path: str, payload: Dict[str, Any]) -> None:
    """Durable small-file write: temp sibling + fsync + rename.  Used for
    streaming offsets and other single-file progress markers."""
    tmp = f"{path}.tmp-{os.getpid()}-{uuid.uuid4().hex[:8]}"
    with open(tmp, "w") as fh:
        json.dump(payload, fh, indent=2, default=str)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)
    _fsync_path(os.path.dirname(os.path.abspath(path)))


# --------------------------------------------------------------------------
# atomic bundle write + manifest
# --------------------------------------------------------------------------

def write_manifest(dirpath: str, extra: Optional[Dict[str, Any]] = None
                   ) -> Dict[str, Any]:
    """Digest every file under ``dirpath`` (recursively — AOT executables
    live in per-platform subdirectories) into a ``MANIFEST.json``, keyed by
    POSIX-style relative path so digests verify on any host."""
    files: Dict[str, Dict[str, Any]] = {}
    for root, dirs, names in os.walk(dirpath):
        dirs.sort()
        rel_root = os.path.relpath(root, dirpath)
        for name in sorted(names):
            rel = name if rel_root == "." else f"{rel_root}/{name}"
            rel = rel.replace(os.sep, "/")
            p = os.path.join(root, name)
            if rel == MANIFEST_NAME or not os.path.isfile(p):
                continue
            files[rel] = {"sha256": _sha256_file(p),
                          "bytes": os.path.getsize(p)}
    manifest: Dict[str, Any] = {"formatVersion": BUNDLE_FORMAT_VERSION,
                                "createdAt": time.time(), "files": files}
    if extra:
        manifest.update(extra)
    mpath = os.path.join(dirpath, MANIFEST_NAME)
    with open(mpath, "w") as fh:
        json.dump(manifest, fh, indent=2)
        fh.flush()
        os.fsync(fh.fileno())
    return manifest


@contextmanager
def atomic_bundle_write(path: str, overwrite: bool = True,
                        manifest_extra: Optional[Dict[str, Any]] = None):
    """Write a bundle directory atomically.

    Yields a temp sibling directory the caller populates; on clean exit the
    manifest is written, everything is fsynced, and the temp directory is
    renamed over ``path`` (the previous bundle, if any, is swapped out and
    removed only after the new one is in place).  On ANY failure — including
    an injected ``checkpoint.save`` fault — the temp directory is discarded
    and the previous bundle at ``path`` is untouched."""
    from .telemetry import span
    path = os.path.abspath(path)
    parent = os.path.dirname(path)
    os.makedirs(parent, exist_ok=True)
    if (not overwrite and os.path.isdir(path) and os.listdir(path)):
        raise FileExistsError(
            f"model directory {path!r} is not empty; pass overwrite=True "
            "to replace it")
    tmp = os.path.join(
        parent,
        f".{os.path.basename(path)}.tmp-{os.getpid()}-{uuid.uuid4().hex[:8]}")
    os.makedirs(tmp)
    try:
        with span("checkpoint.save", bundle=os.path.basename(path)):
            yield tmp
            # chaos hook: a fault here simulates the process dying after the
            # data files are written but before the bundle commits
            maybe_inject("checkpoint.save", key=os.path.basename(path))
            write_manifest(tmp, extra=manifest_extra)
            for root, _dirs, names in os.walk(tmp, topdown=False):
                for name in names:
                    _fsync_path(os.path.join(root, name))
                _fsync_path(root)
            if os.path.lexists(path):
                old = f"{tmp}.old"
                os.rename(path, old)
                os.rename(tmp, path)
                shutil.rmtree(old, ignore_errors=True)
            else:
                os.rename(tmp, path)
            _fsync_path(parent)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise


# --------------------------------------------------------------------------
# verification + checkpoint-root fallback
# --------------------------------------------------------------------------

def read_manifest(path: str) -> Optional[Dict[str, Any]]:
    """The bundle's manifest dict, or None for a legacy unversioned bundle."""
    mpath = os.path.join(path, MANIFEST_NAME)
    if not os.path.exists(mpath):
        return None
    try:
        with open(mpath) as fh:
            return json.load(fh)
    except (OSError, ValueError) as e:
        raise CorruptModelError(path, MANIFEST_NAME,
                                f"unreadable manifest ({e})") from e


def verify_bundle(path: str) -> Optional[Dict[str, Any]]:
    """Verify a bundle directory's format version and per-file digests.

    Returns the manifest (None for a legacy bundle without one); raises
    ``ModelVersionError`` on version skew and ``CorruptModelError`` naming
    the first missing/mismatched file.  Files present in the directory but
    not listed in the manifest (e.g. a side-written summary) are ignored."""
    from .telemetry import span
    with span("checkpoint.load", bundle=os.path.basename(path)):
        maybe_inject("checkpoint.load", key=os.path.basename(path))
        if not os.path.isdir(path):
            raise FileNotFoundError(
                f"model bundle directory {path!r} does not exist")
        manifest = read_manifest(path)
        if manifest is None:
            return None
        version = manifest.get("formatVersion")
        if not isinstance(version, int) \
                or not 1 <= version <= BUNDLE_FORMAT_VERSION:
            raise ModelVersionError(path, version)
        for name, info in (manifest.get("files") or {}).items():
            fpath = os.path.join(path, name)
            if not os.path.exists(fpath):
                raise CorruptModelError(
                    path, name, "listed in MANIFEST but missing on disk")
            digest = _sha256_file(fpath)
            if digest != info.get("sha256"):
                raise CorruptModelError(
                    path, name, f"SHA-256 mismatch (manifest "
                    f"{str(info.get('sha256'))[:12]}…, disk {digest[:12]}…)")
        return manifest


def is_bundle_dir(path: str) -> bool:
    """Does ``path`` look like a single model bundle (vs a checkpoint root)?"""
    return os.path.isdir(path) and (
        os.path.exists(os.path.join(path, MANIFEST_NAME))
        or os.path.exists(os.path.join(path, "op-model.json")))


def _bundle_sort_key(path: str) -> float:
    try:
        m = read_manifest(path)
        if m and isinstance(m.get("createdAt"), (int, float)):
            return float(m["createdAt"])
    except CheckpointError:
        pass
    try:
        return os.path.getmtime(path)
    except OSError:
        return 0.0


def find_latest_valid(root: str) -> str:
    """Newest sub-bundle under ``root`` that passes verification.

    Invalid/corrupt candidates are reported to the ambient ``FailureLog``
    (action ``skipped``, point ``checkpoint.load``) and the scan continues;
    raises ``CorruptModelError`` when nothing under the root verifies."""
    if not os.path.isdir(root):
        raise FileNotFoundError(
            f"checkpoint root {root!r} does not exist")
    candidates = [os.path.join(root, n) for n in os.listdir(root)
                  if is_bundle_dir(os.path.join(root, n))]
    if not candidates:
        raise FileNotFoundError(
            f"model directory {root!r} contains neither a model bundle "
            f"(no op-model.json / {MANIFEST_NAME}) nor any checkpoint "
            "sub-directories")
    for cand in sorted(candidates, key=_bundle_sort_key, reverse=True):
        try:
            verify_bundle(cand)
            return cand
        except (CheckpointError, FileNotFoundError) as e:
            record_failure("checkpoint", "skipped", e,
                           point="checkpoint.load", bundle=cand)
    raise CorruptModelError(
        root, "", f"no valid checkpoint under root (tried "
        f"{len(candidates)} candidate(s); see failure log for causes)")


def bundle_version(path: str) -> str:
    """Stable identity of a bundle for serving: its directory basename plus
    the manifest's createdAt when present (``ckpt-000002@1722800000``).  Two
    loads of the same bundle compare equal; a rewritten bundle does not."""
    base = os.path.basename(os.path.normpath(path))
    try:
        m = read_manifest(path)
    except CheckpointError:
        m = None
    created = (m or {}).get("createdAt")
    if isinstance(created, (int, float)):
        return f"{base}@{int(created)}"
    return base


def next_version_dir(root: str) -> str:
    """The next ``ckpt-NNNNNN`` directory name under a checkpoint root."""
    os.makedirs(root, exist_ok=True)
    ids = []
    for n in os.listdir(root):
        if n.startswith(_VERSION_DIR_PREFIX):
            try:
                ids.append(int(n[len(_VERSION_DIR_PREFIX):]))
            except ValueError:
                pass
    return os.path.join(root, f"{_VERSION_DIR_PREFIX}{max(ids, default=0) + 1:06d}")


def prune_versions(root: str, keep: int) -> List[str]:
    """Remove the oldest version directories beyond ``keep``; returns the
    removed paths.  Never removes a bundle it cannot order."""
    if keep < 1:
        raise ValueError("keep must be >= 1")
    versions = sorted(
        (os.path.join(root, n) for n in os.listdir(root)
         if n.startswith(_VERSION_DIR_PREFIX)
         and os.path.isdir(os.path.join(root, n))),
        key=_bundle_sort_key, reverse=True)
    removed = []
    for path in versions[keep:]:
        shutil.rmtree(path, ignore_errors=True)
        removed.append(path)
    return removed


# --------------------------------------------------------------------------
# resumable selector sweeps
# --------------------------------------------------------------------------

_SWEEP_JSON = "sweep.json"
_SWEEP_NPZ = "sweep.npz"


class SweepCheckpoint:
    """Durable record of completed selector-sweep candidates.

    One bundle directory (atomic + checksummed like any model bundle)
    holding ``sweep.json`` — per-candidate grid scores keyed by a content
    signature of (model name, candidate index, grid) — and ``sweep.npz``
    with the candidates' fitted arrays, split JSON-vs-npz the same way the
    stage ``save_extra`` machinery splits stage state.  A candidate whose
    signature is present is *complete*: a resumed sweep replays its scores
    instead of re-fitting it."""

    def __init__(self, path: str):
        self.path = os.path.abspath(path)
        self._candidates: Dict[str, Dict[str, Any]] = {}
        self._arrays: Dict[str, np.ndarray] = {}
        self.winner: Optional[Dict[str, Any]] = None
        if os.path.isdir(self.path) and \
                os.path.exists(os.path.join(self.path, _SWEEP_JSON)):
            self._load()

    # -- identity ----------------------------------------------------------
    @staticmethod
    def candidate_signature(model_name: str, candidate_index: int,
                            grid: Sequence[Dict[str, Any]],
                            racing: Optional[Dict[str, Any]] = None) -> str:
        """Content hash of a candidate: a resumed run only replays a result
        if the model, its position, its full grid, AND the sweep's racing
        configuration are unchanged — a raced family's pruned points carry
        fold-0-only score lists, which must never replay into (or out of)
        an unraced sweep."""
        payload = json.dumps(
            {"model": model_name, "index": int(candidate_index),
             "grid": [dict(sorted(g.items())) for g in grid],
             "racing": dict(sorted((racing or {}).items()))},
            sort_keys=True, default=str)
        return hashlib.sha256(payload.encode()).hexdigest()[:16]

    # -- persistence -------------------------------------------------------
    def _load(self) -> None:
        verify_bundle(self.path)
        with open(os.path.join(self.path, _SWEEP_JSON)) as fh:
            data = json.load(fh)
        self._candidates = dict(data.get("candidates") or {})
        self.winner = data.get("winner")
        npz = os.path.join(self.path, _SWEEP_NPZ)
        if os.path.exists(npz):
            self._arrays = dict(np.load(npz, allow_pickle=False))

    def flush(self) -> None:
        """Atomically rewrite the whole sweep bundle."""
        with atomic_bundle_write(self.path, overwrite=True,
                                 manifest_extra={"kind": "selector-sweep"}
                                 ) as tmp:
            with open(os.path.join(tmp, _SWEEP_JSON), "w") as fh:
                json.dump({"formatVersion": BUNDLE_FORMAT_VERSION,
                           "candidates": self._candidates,
                           "winner": self.winner}, fh, indent=2, default=str)
            np.savez_compressed(os.path.join(tmp, _SWEEP_NPZ), **self._arrays)

    # -- candidate results -------------------------------------------------
    def __contains__(self, sig: str) -> bool:
        return sig in self._candidates

    def __len__(self) -> int:
        return len(self._candidates)

    def results_for(self, sig: str) -> Optional[List[Dict[str, Any]]]:
        entry = self._candidates.get(sig)
        return None if entry is None else list(entry.get("results") or [])

    def record_candidate(self, sig: str, model_name: str,
                         candidate_index: int,
                         results: Sequence[Dict[str, Any]],
                         fitted_grid: Optional[Sequence[Sequence[Any]]] = None
                         ) -> None:
        """Add a completed candidate: ``results`` is the per-grid-point
        score list ``[{"params": ..., "metricValues": [...]}]``; the fitted
        (fold × grid) state, when given, splits into JSON scalars +
        npz arrays exactly like stage ``save_extra`` state."""
        from .stages.serialization import _is_array, _json_safe

        entry: Dict[str, Any] = {
            "modelName": model_name, "candidateIndex": int(candidate_index),
            "results": [dict(r) for r in results]}
        if fitted_grid is not None:
            fitted_json: List[List[Optional[Dict[str, Any]]]] = []
            for f, row in enumerate(fitted_grid):
                jrow: List[Optional[Dict[str, Any]]] = []
                for g, fitted in enumerate(row):
                    if not isinstance(fitted, dict):
                        jrow.append(None)
                        continue
                    cell: Dict[str, Any] = {}
                    for k, v in fitted.items():
                        if _is_array(v):
                            self._arrays[f"{sig}/f{f}/g{g}/{k}"] = \
                                np.asarray(v)
                        else:
                            cell[k] = _json_safe(v)
                    jrow.append(cell)
                fitted_json.append(jrow)
            entry["fittedJson"] = fitted_json
        self._candidates[sig] = entry

    def fitted_grid(self, sig: str) -> Optional[List[List[Any]]]:
        """Reconstruct a completed candidate's (fold × grid) fitted state."""
        entry = self._candidates.get(sig)
        if entry is None or "fittedJson" not in entry:
            return None
        out: List[List[Any]] = []
        for f, jrow in enumerate(entry["fittedJson"]):
            row: List[Any] = []
            for g, cell in enumerate(jrow):
                if cell is None:
                    row.append(None)
                    continue
                fitted = dict(cell)
                prefix = f"{sig}/f{f}/g{g}/"
                for k, v in self._arrays.items():
                    if k.startswith(prefix):
                        fitted[k[len(prefix):]] = v
                row.append(fitted)
            out.append(row)
        return out

    def set_winner(self, model_name: str, params: Dict[str, Any],
                   metric: float) -> None:
        self.winner = {"modelName": model_name, "params": dict(params),
                       "metric": float(metric)}
        self.flush()


# Ambient sweep checkpoint, mirroring resilience.use_failure_log: installed
# by Workflow.train for its dynamic extent so the validator — reached through
# the stage-fit plumbing — can pick it up without signature changes.
_SWEEP_STACK: List[SweepCheckpoint] = []
_SWEEP_LOCK = threading.Lock()


def active_sweep_checkpoint() -> Optional[SweepCheckpoint]:
    with _SWEEP_LOCK:
        return _SWEEP_STACK[-1] if _SWEEP_STACK else None


@contextmanager
def use_sweep_checkpoint(cp: Optional[SweepCheckpoint]):
    if cp is None:
        yield None
        return
    with _SWEEP_LOCK:
        _SWEEP_STACK.append(cp)
    try:
        yield cp
    finally:
        with _SWEEP_LOCK:
            for i in range(len(_SWEEP_STACK) - 1, -1, -1):
                if _SWEEP_STACK[i] is cp:
                    del _SWEEP_STACK[i]
                    break


# --------------------------------------------------------------------------
# preemption-aware shutdown
# --------------------------------------------------------------------------

class PreemptionGuard:
    """Cooperative stop flag set by SIGTERM/SIGINT (or injected preemption).

    Loops poll ``shutdown_requested()`` at their candidate/batch boundaries
    and wind down gracefully — flushing checkpoints and offsets — instead of
    dying mid-write."""

    def __init__(self, stage: str = "train"):
        self.stage = stage
        self.stop_requested = False
        self.reason = ""

    def request_stop(self, reason: Any) -> None:
        if not self.stop_requested:
            self.stop_requested = True
            self.reason = str(reason)
            record_failure(self.stage, "preempted", reason,
                           point="preemption")


_GUARD: Optional[PreemptionGuard] = None
_GUARD_DEPTH = 0
_GUARD_LOCK = threading.Lock()
_PREV_HANDLERS: Dict[int, Any] = {}


def _signal_handler(signum, frame):  # pragma: no cover — exercised via kill
    guard = _GUARD
    if guard is None:
        return
    if guard.stop_requested:
        # second signal: the operator really means it
        raise KeyboardInterrupt(
            f"second signal {signum} during graceful shutdown")
    guard.request_stop(f"signal {signum}")


@contextmanager
def preemption_guard(stage: str = "train",
                     signals: Sequence[int] = (signal.SIGTERM, signal.SIGINT)):
    """Install the SIGTERM/SIGINT → graceful-stop handler for the dynamic
    extent.  Re-entrant: nested guards (runner → train) share one flag and
    only the outermost install/restore touches the handlers.  Off the main
    thread — where Python forbids signal() — the guard still works for
    injected preemptions and records the degradation."""
    global _GUARD, _GUARD_DEPTH
    with _GUARD_LOCK:
        _GUARD_DEPTH += 1
        if _GUARD is None:
            _GUARD = PreemptionGuard(stage)
            try:
                for s in signals:
                    _PREV_HANDLERS[s] = signal.signal(s, _signal_handler)
            except ValueError as e:   # not the main thread
                record_failure(stage, "degraded", e,
                               point="preemption.install",
                               fallback="injection-only preemption checks")
        guard = _GUARD
    try:
        yield guard
    finally:
        with _GUARD_LOCK:
            _GUARD_DEPTH -= 1
            if _GUARD_DEPTH == 0:
                for s, h in _PREV_HANDLERS.items():
                    try:
                        signal.signal(s, h)
                    except (ValueError, OSError):
                        pass
                _PREV_HANDLERS.clear()
                _GUARD = None


def shutdown_requested(key: Any = None) -> bool:
    """Has a graceful stop been requested (signal or injected fault)?

    The one-liner loops call at their boundaries: ``key`` identifies the
    unit of work about to start (candidate name, batch index) so chaos
    tests can preempt at an exact boundary via the ``preemption``
    injection point."""
    guard = _GUARD
    if guard is not None and guard.stop_requested:
        return True
    try:
        maybe_inject("preemption", key=key)
    except InjectedFault as e:
        if guard is not None:
            guard.request_stop(e)
        else:
            record_failure("preemption", "preempted", e, point="preemption")
        return True
    return False
