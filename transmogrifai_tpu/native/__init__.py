"""Native runtime components (C++), built lazily with the system toolchain.

The reference's ingestion/runtime layer is JVM code running on Spark
executors; this framework's equivalent native layer lives here.  Modules are
compiled on first use with ``g++`` (no pip/network), cached next to the
package, and every consumer has a pure-Python fallback — absence of a
toolchain degrades performance, never correctness.
"""

from __future__ import annotations

import importlib.util
import os
import subprocess
import sys
import sysconfig
from typing import Any, Optional

_CACHE: dict = {}


def _build_dir() -> str:
    d = os.path.join(os.path.dirname(os.path.abspath(__file__)), "_build")
    os.makedirs(d, exist_ok=True)
    return d


def _source_path(name: str) -> str:
    # native/ sources live at the repo root next to the package
    pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.join(os.path.dirname(pkg_root), "native", f"{name}.cpp")


def _compile(name: str) -> Optional[str]:
    src = _source_path(name)
    if not os.path.exists(src):
        return None
    so = os.path.join(_build_dir(), f"_{name}.so")
    if os.path.exists(so) and os.path.getmtime(so) >= os.path.getmtime(src):
        return so
    import numpy as np
    cmd = [
        os.environ.get("CXX", "g++"), "-O2", "-std=c++17", "-shared", "-fPIC",
        f"-I{sysconfig.get_paths()['include']}",
        f"-I{np.get_include()}",
        src, "-o", so,
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
    except Exception:  # pragma: no cover — toolchain-dependent
        return None
    return so


def load(name: str) -> Optional[Any]:
    """Import native module ``_<name>``, compiling it if needed.  Returns the
    module or None (callers fall back to pure Python).  Disable with
    TRANSMOGRIFAI_NATIVE=0."""
    if name in _CACHE:
        return _CACHE[name]
    mod = None
    if os.environ.get("TRANSMOGRIFAI_NATIVE", "1") != "0":
        try:
            so = _compile(name)
            if so is not None:
                spec = importlib.util.spec_from_file_location(f"_{name}", so)
                if spec and spec.loader:
                    mod = importlib.util.module_from_spec(spec)
                    sys.modules[f"_{name}"] = mod
                    spec.loader.exec_module(mod)
        except Exception:  # pragma: no cover — best-effort native path
            mod = None
    _CACHE[name] = mod
    return mod
