"""Per-column lineage metadata for assembled feature vectors — the TPU-native
equivalent of OpVectorMetadata / OpVectorColumnMetadata (reference:
features/src/main/scala/com/salesforce/op/utils/spark/OpVectorColumnMetadata.scala:67).

Every vectorizer emits, alongside its [N, D] array, one ``VectorColumnMeta`` per
output column recording which raw feature it came from, the grouping (e.g. the
categorical value pivoted on), and indicator info.  This is the backbone of the
SanityChecker feature-drop reports and ModelInsights.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Dict, List, Optional, Sequence

NULL_INDICATOR = "NullIndicatorValue"   # cf. OpVectorColumnMetadata.NullString
OTHER_INDICATOR = "OTHER"


@dataclass(frozen=True)
class VectorColumnMeta:
    """One column of an assembled feature vector."""

    parent_feature_name: str
    parent_feature_type: str
    grouping: Optional[str] = None          # e.g. map key or categorical group
    indicator_value: Optional[str] = None   # pivoted categorical value / null flag
    descriptor_value: Optional[str] = None  # e.g. "sin(dayOfWeek)" for date circles
    index: int = 0

    def make_col_name(self) -> str:
        parts = [self.parent_feature_name]
        if self.grouping:
            parts.append(self.grouping)
        if self.indicator_value:
            parts.append(self.indicator_value)
        elif self.descriptor_value:
            parts.append(self.descriptor_value)
        return "_".join(parts) + f"_{self.index}"

    @property
    def is_null_indicator(self) -> bool:
        return self.indicator_value == NULL_INDICATOR

    @property
    def is_other_indicator(self) -> bool:
        return self.indicator_value == OTHER_INDICATOR

    def to_json(self) -> Dict:
        return {
            "parentFeatureName": self.parent_feature_name,
            "parentFeatureType": self.parent_feature_type,
            "grouping": self.grouping,
            "indicatorValue": self.indicator_value,
            "descriptorValue": self.descriptor_value,
            "index": self.index,
        }

    @staticmethod
    def from_json(d: Dict) -> "VectorColumnMeta":
        return VectorColumnMeta(
            parent_feature_name=d["parentFeatureName"],
            parent_feature_type=d["parentFeatureType"],
            grouping=d.get("grouping"),
            indicator_value=d.get("indicatorValue"),
            descriptor_value=d.get("descriptorValue"),
            index=d.get("index", 0),
        )


@dataclass
class VectorMeta:
    """Metadata for a whole feature vector (≙ OpVectorMetadata)."""

    name: str
    columns: List[VectorColumnMeta] = field(default_factory=list)

    def __post_init__(self):
        self.columns = [replace(c, index=i) for i, c in enumerate(self.columns)]

    @property
    def size(self) -> int:
        return len(self.columns)

    def column_names(self) -> List[str]:
        return [c.make_col_name() for c in self.columns]

    def parent_features(self) -> List[str]:
        seen, out = set(), []
        for c in self.columns:
            if c.parent_feature_name not in seen:
                seen.add(c.parent_feature_name)
                out.append(c.parent_feature_name)
        return out

    def index_by_parent(self) -> Dict[str, List[int]]:
        out: Dict[str, List[int]] = {}
        for c in self.columns:
            out.setdefault(c.parent_feature_name, []).append(c.index)
        return out

    def select(self, indices: Sequence[int], name: Optional[str] = None) -> "VectorMeta":
        return VectorMeta(name or self.name, [self.columns[i] for i in indices])

    @staticmethod
    def flatten(name: str, metas: Sequence["VectorMeta"]) -> "VectorMeta":
        cols: List[VectorColumnMeta] = []
        for m in metas:
            cols.extend(m.columns)
        return VectorMeta(name, cols)

    def to_json(self) -> Dict:
        return {"name": self.name, "columns": [c.to_json() for c in self.columns]}

    @staticmethod
    def from_json(d: Dict) -> "VectorMeta":
        return VectorMeta(d["name"], [VectorColumnMeta.from_json(c) for c in d["columns"]])
