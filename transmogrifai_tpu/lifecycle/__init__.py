"""Production lifecycle: drift detection → gated retrain → atomic hot-swap.

Closes the train → monitor → retrain → promote loop over the existing
subsystems: bundle-embedded training baselines (``baselines``), a
streaming-sketch drift monitor fed from the serving path (``drift``), a
policy-driven retrain controller with holdout-gated promotion
(``controller``), and the runner/CLI glue (``service``).
"""

from .baselines import (BASELINES_JSON, ModelBaselines,  # noqa: F401
                        build_baselines, load_baselines)
from .controller import (DriftThresholdPolicy,  # noqa: F401
                         LifecycleController, LifecycleOutcome,
                         LifecycleState, ManualPolicy, RetrainPolicy,
                         ScheduledIntervalPolicy, rank_tenants_for_retrain)
from .drift import DriftMonitor, DriftReport, psi  # noqa: F401
from .service import drift_check_main, lifecycle_main  # noqa: F401
