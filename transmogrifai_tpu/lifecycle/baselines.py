"""Training-time drift baselines bundled with every saved model.

At ``WorkflowModel.save`` time the post-fit training batch is still on the
model (``train_batch``), so the per-raw-feature ``FeatureSketch``es (streaming
histograms for numeric kinds, stable-hash bins for text — filters.py) and the
score distribution can be serialized into the bundle as ``baselines.json``.
``atomic_bundle_write`` digests every staged file into ``MANIFEST.json``, so
the baselines are integrity-covered exactly like the model weights.

At serving time ``DriftMonitor`` (lifecycle/drift.py) deserializes these and
compares the live feed against them with the same streaming-histogram merge
semantics the training-side filters use.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Tuple

import numpy as np

from ..filters import FeatureSketch, compute_sketches
from ..utils.stats import StreamingHistogram

BASELINES_JSON = "baselines.json"
FORMAT_VERSION = 1


@dataclass
class ModelBaselines:
    """What the training data looked like, in mergeable-sketch form."""

    features: Dict[Tuple[str, Optional[str]], FeatureSketch] = \
        field(default_factory=dict)
    score_histogram: Optional[StreamingHistogram] = None
    score_feature: Optional[str] = None   # Prediction column name
    score_field: Optional[str] = None     # e.g. "probability_1"/"prediction"
    row_count: int = 0
    max_bins: int = 64
    text_bins: int = 100                  # live sketches must match this

    def to_json(self) -> Dict[str, Any]:
        return {"formatVersion": FORMAT_VERSION,
                "rowCount": int(self.row_count),
                "maxBins": int(self.max_bins),
                "textBins": int(self.text_bins),
                "scoreFeature": self.score_feature,
                "scoreField": self.score_field,
                "scoreHistogram": (self.score_histogram.to_json()
                                   if self.score_histogram is not None
                                   else None),
                "features": [sk.to_json() for sk in self.features.values()]}

    @staticmethod
    def from_json(d: Dict[str, Any]) -> "ModelBaselines":
        feats: Dict[Tuple[str, Optional[str]], FeatureSketch] = {}
        for sd in d.get("features") or []:
            sk = FeatureSketch.from_json(sd)
            feats[(sk.name, sk.key)] = sk
        hist = None
        if d.get("scoreHistogram") is not None:
            hist = StreamingHistogram.from_json(d["scoreHistogram"])
        return ModelBaselines(
            features=feats, score_histogram=hist,
            score_feature=d.get("scoreFeature"),
            score_field=d.get("scoreField"),
            row_count=int(d.get("rowCount", 0)),
            max_bins=int(d.get("maxBins", 64)),
            text_bins=int(d.get("textBins", 100)))

    def save(self, dirpath: str) -> str:
        """Write ``baselines.json`` into a bundle staging directory (called
        inside ``atomic_bundle_write``, so the digest covers it)."""
        out = os.path.join(dirpath, BASELINES_JSON)
        with open(out, "w") as fh:
            json.dump(self.to_json(), fh)
        return out


def build_baselines(model, max_bins: int = 64,
                    text_bins: int = 100) -> Optional[ModelBaselines]:
    """Sketch the model's retained training batch; ``None`` when the model
    has no training batch (e.g. it was loaded from disk and re-saved)."""
    batch = getattr(model, "train_batch", None)
    if batch is None or len(batch) == 0:
        return None
    feats = [f for f in model.raw_features
             if not f.is_response and batch.get(f.name) is not None]
    if not feats:
        return None
    sketches = compute_sketches(feats, batch, max_bins=max_bins,
                                text_bins=text_bins)
    score_hist = score_feature = score_field = None
    from ..types import Prediction
    pred = next((f for f in model.result_features
                 if f.kind is Prediction and batch.get(f.name) is not None),
                None)
    if pred is not None:
        vals = batch[pred.name].values
        if isinstance(vals, dict) and vals:
            score_field = ("probability_1" if "probability_1" in vals
                           else "prediction" if "prediction" in vals
                           else next(iter(vals)))
            arr = np.asarray(vals[score_field], dtype=np.float64)
            score_hist = StreamingHistogram(max_bins).update_all(arr)
            score_feature = pred.name
    return ModelBaselines(features=sketches, score_histogram=score_hist,
                          score_feature=score_feature,
                          score_field=score_field, row_count=len(batch),
                          max_bins=max_bins, text_bins=text_bins)


def load_baselines(bundle_path: str) -> Optional[ModelBaselines]:
    """Read a bundle's ``baselines.json``; ``None`` when the bundle predates
    the lifecycle subsystem (drift monitoring is then disabled)."""
    path = os.path.join(bundle_path, BASELINES_JSON)
    if not os.path.exists(path):
        return None
    with open(path) as fh:
        return ModelBaselines.from_json(json.load(fh))
