"""Lifecycle service glue — StreamingReader live feed → DriftMonitor →
LifecycleController, wired for the runner (``--run-type lifecycle``) and the
``lifecycle`` CLI subcommand (one-shot drift check).

``lifecycle_main`` is the runner entry point: it seeds the serving root with
a first trained bundle when empty, builds the drift monitor from the
incumbent's baselines, pumps live micro-batches (with shadow scoring for
score-distribution PSI), and runs bounded controller iterations under
``preemption_guard``.  Knobs ride in ``OpParams.lifecycle``
("lifecycleParams"): ``psiThreshold``, ``scorePsiThreshold``,
``fillDeltaThreshold``, ``minRows``, ``tolerance``, ``policy``
(``drift``/``interval``), ``intervalS``, ``forceRetrain``,
``maxIterations``, ``batchesPerCheck``, ``pollS``, ``warmStart``.
"""

from __future__ import annotations

import json
import time
from typing import Any, Dict, List, Optional

import numpy as np

from ..checkpoint import (find_latest_valid, next_version_dir,
                          preemption_guard, shutdown_requested)
from ..resilience import FailureLog, record_failure, use_failure_log
from ..telemetry import event, span
from .controller import (DriftThresholdPolicy, LifecycleController,
                         ManualPolicy, RetrainPolicy, ScheduledIntervalPolicy)
from .drift import DriftMonitor


def pump_stream(monitor: DriftMonitor, stream, shadow_model=None,
                max_batches: Optional[int] = None) -> int:
    """Feed live micro-batches into the monitor; with ``shadow_model`` the
    batch is also scored so score-distribution PSI sees the live feed even
    when no serving engine is attached.  Returns batches consumed."""
    n = 0
    for batch in stream:
        if max_batches is not None and n >= max_batches:
            break
        monitor.observe_batch(batch)
        if shadow_model is not None and monitor.enabled and \
                monitor.baselines.score_feature is not None:
            try:
                scored = shadow_model.score(batch=batch)
                col = scored.get(monitor.baselines.score_feature)
                if col is not None and isinstance(col.values, dict):
                    vals = col.values.get(monitor.baselines.score_field)
                    if vals is None:
                        vals = col.values.get("prediction")
                    if vals is not None:
                        monitor.observe_scores(
                            np.asarray(vals, dtype=np.float64))
            except Exception as e:  # noqa: BLE001 — shadow scoring is
                #                     best-effort observability
                record_failure("lifecycle", "swallowed", e,
                               point="drift.observe")
        n += 1
    return n


def _build_policies(cfg: Dict[str, Any],
                    monitor: Optional[DriftMonitor]) -> List[RetrainPolicy]:
    policies: List[RetrainPolicy] = []
    if cfg.get("forceRetrain"):
        manual = ManualPolicy()
        manual.trigger("forced retrain (lifecycleParams.forceRetrain)")
        policies.append(manual)
    policy = cfg.get("policy", "drift")
    if policy == "interval" or cfg.get("intervalS") is not None:
        policies.append(
            ScheduledIntervalPolicy(float(cfg.get("intervalS", 3600.0))))
    if policy == "drift" and monitor is not None:
        policies.append(DriftThresholdPolicy(
            min_interval_s=float(cfg.get("minRetrainIntervalS", 0.0))))
    return policies


def lifecycle_main(workflow, root: str, *, evaluator=None, live_reader=None,
                   holdout_reader=None, engine=None,
                   config: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """Bounded lifecycle loop; returns a JSON-able run summary."""
    from ..workflow import WorkflowModel
    cfg = dict(config or {})
    if evaluator is None:
        from ..evaluators import OpBinaryClassificationEvaluator
        evaluator = OpBinaryClassificationEvaluator()
    flog = FailureLog()
    outcomes: List[Optional[Dict[str, Any]]] = []
    ingested = 0
    with use_failure_log(flog), preemption_guard("lifecycle"), \
            span("lifecycle.run", root=root):
        # seed: an empty root gets a first trained bundle, so there is
        # always an incumbent to monitor and gate against
        try:
            latest = find_latest_valid(root)
        except Exception:  # noqa: BLE001 — empty or absent root
            seed = workflow.train()
            latest = next_version_dir(root)
            seed.save(latest)
            event("lifecycle.seeded", bundle=latest)
        incumbent = WorkflowModel.load(latest)
        from ..telemetry import REGISTRY
        monitor = DriftMonitor.for_model(
            incumbent, registry=REGISTRY,
            psi_threshold=float(cfg.get("psiThreshold", 0.25)),
            score_psi_threshold=float(cfg.get("scorePsiThreshold", 0.25)),
            fill_delta_threshold=float(cfg.get("fillDeltaThreshold", 0.2)),
            min_rows=int(cfg.get("minRows", 50)),
            bins=int(cfg.get("bins", 10)))
        if live_reader is not None and \
                hasattr(live_reader, "set_raw_features"):
            live_reader.set_raw_features(
                [f for f in incumbent.raw_features if not f.is_response])
        controller = LifecycleController(
            lambda: workflow, root, evaluator,
            holdout_reader=holdout_reader or workflow.reader,
            monitor=monitor, policies=_build_policies(cfg, monitor),
            engine=engine, tolerance=float(cfg.get("tolerance", 0.0)),
            warm_start=bool(cfg.get("warmStart", True)))
        stream = (iter(live_reader.stream())
                  if live_reader is not None and
                  hasattr(live_reader, "stream") else None)
        per_check = cfg.get("batchesPerCheck")
        per_check = int(per_check) if per_check is not None else None
        iterations = int(cfg.get("maxIterations", 1))
        shadow = incumbent
        from ..obsv import BOARD
        for i in range(iterations):
            if shutdown_requested(key=f"lifecycle-{i}"):
                break
            BOARD.publish(phase="lifecycle", lifecycleIteration=i,
                          lifecycleIterations=iterations,
                          batchesIngested=ingested)
            if stream is not None and monitor is not None:
                ingested += pump_stream(monitor, stream, shadow_model=shadow,
                                        max_batches=per_check)
            outcome = controller.run_once()
            outcomes.append(outcome.to_json() if outcome else None)
            BOARD.publish(lastLifecycleOutcome=(outcome.status
                                                if outcome else None))
            if outcome is not None and outcome.status == "promoted" and \
                    outcome.candidate_path:
                shadow = WorkflowModel.load(outcome.candidate_path)
            if i + 1 < iterations and cfg.get("pollS"):
                time.sleep(float(cfg["pollS"]))
    return {"root": root, "iterations": len(outcomes),
            "batchesIngested": ingested,
            "state": controller.state.to_json(), "outcomes": outcomes,
            "driftReport": (monitor.last_report.to_json()
                            if monitor is not None and
                            monitor.last_report is not None else None),
            "driftEnabled": monitor is not None,
            "failures": flog.summary()}


def drift_check_main(model_location: str, records_path: str, *,
                     psi_threshold: float = 0.25,
                     score_psi_threshold: float = 0.25,
                     fill_delta_threshold: float = 0.2, min_rows: int = 50,
                     shadow_score: bool = False, out=print) -> int:
    """``lifecycle`` CLI subcommand: drift-check a JSONL sample of raw
    records against a saved model's baselines.  Exit codes: 0 ok, 2 drift
    breach, 3 baselines missing (drift disabled)."""
    from ..workflow import WorkflowModel
    model = WorkflowModel.load(model_location)
    monitor = DriftMonitor.for_model(
        model, psi_threshold=psi_threshold,
        score_psi_threshold=score_psi_threshold,
        fill_delta_threshold=fill_delta_threshold, min_rows=min_rows)
    if monitor is None:
        out(json.dumps({"enabled": False,
                        "reason": "bundle has no baselines.json (saved by a "
                                  "pre-lifecycle build)"}, indent=2))
        return 3
    with open(records_path) as fh:
        records = [json.loads(line) for line in fh if line.strip()]
    monitor.observe_records(records)
    if shadow_score and monitor.baselines.score_feature is not None:
        from ..readers import DataReader
        batch = DataReader(records=records).generate_batch(
            monitor.raw_features)
        try:
            scored = model.score(batch=batch)
            col = scored.get(monitor.baselines.score_feature)
            if col is not None and isinstance(col.values, dict):
                vals = col.values.get(monitor.baselines.score_field)
                if vals is not None:
                    monitor.observe_scores(np.asarray(vals,
                                                      dtype=np.float64))
        except Exception as e:  # noqa: BLE001
            record_failure("lifecycle", "swallowed", e, point="drift.observe")
    report = monitor.evaluate()
    out(json.dumps(report.to_json(), indent=2))
    return 2 if report.breached else 0
