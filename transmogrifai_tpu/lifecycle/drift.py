"""DriftMonitor — live feature/score distributions vs. bundle baselines.

Fed from the serving path (a ``ScoringEngine`` batch observer) or from a
``StreamingReader`` pump, the monitor accumulates the SAME mergeable
``FeatureSketch``es the training-side filters build (``compute_sketches`` +
``merge_sketches``), then ``evaluate()`` compares them against the bundle's
training-time baselines:

* per-feature fill-rate delta,
* per-feature PSI + Jensen-Shannon divergence over a SHARED fixed binning
  (the union of both sketches' centroid ranges — without a shared range a
  pure mean shift would bin to near-identical shapes and never fire),
* score-distribution PSI.

Results export through a ``MetricsRegistry`` (the engine's, when attached —
they surface on ``/metrics``) and as ``drift.*`` telemetry spans/events.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..filters import (FeatureDistribution, FeatureSketch, compute_sketches,
                       merge_sketches)
from ..telemetry import MetricsRegistry, event, span
from ..utils.stats import StreamingHistogram
from .baselines import ModelBaselines


def psi(expected, actual, eps: float = 1e-4) -> float:
    """Population Stability Index between two binned counts/frequencies.
    Zero-probability bins are clipped to ``eps`` (then renormalized) so a
    bin empty on one side contributes a large-but-finite term."""
    e = np.asarray(expected, dtype=np.float64)
    a = np.asarray(actual, dtype=np.float64)
    if e.size == 0 or a.size == 0 or e.size != a.size:
        return 0.0
    if e.sum() <= 0 or a.sum() <= 0:
        return 0.0
    e = np.clip(e / e.sum(), eps, None)
    a = np.clip(a / a.sum(), eps, None)
    e, a = e / e.sum(), a / a.sum()
    return float(np.sum((a - e) * np.log(a / e)))


def _shared_range(a: StreamingHistogram,
                  b: StreamingHistogram) -> Tuple[float, float]:
    pts = [p for p, _ in a.bins] + [p for p, _ in b.bins]
    if not pts:
        return 0.0, 1.0
    lo, hi = min(pts), max(pts)
    if hi <= lo:
        hi = lo + 1.0
    return lo, hi


def _label(name: str, key: Optional[str]) -> str:
    return name if key is None else f"{name}[{key}]"


@dataclass
class FeatureDriftStat:
    name: str
    key: Optional[str]
    psi: float
    js: float
    fill_rate: float
    baseline_fill_rate: float
    fill_delta: float
    rows: int
    reasons: List[str] = field(default_factory=list)

    @property
    def breached(self) -> bool:
        return bool(self.reasons)

    def to_json(self) -> Dict[str, Any]:
        return {"feature": _label(self.name, self.key), "psi": self.psi,
                "jsDivergence": self.js, "fillRate": self.fill_rate,
                "baselineFillRate": self.baseline_fill_rate,
                "fillDelta": self.fill_delta, "rows": self.rows,
                "breached": self.breached, "reasons": self.reasons}


@dataclass
class DriftReport:
    ready: bool
    rows: int
    score_rows: int
    score_psi: float
    features: List[FeatureDriftStat] = field(default_factory=list)
    reasons: List[str] = field(default_factory=list)

    @property
    def breached(self) -> bool:
        return bool(self.reasons)

    def to_json(self) -> Dict[str, Any]:
        return {"ready": self.ready, "rows": self.rows,
                "scoreRows": self.score_rows, "scorePsi": self.score_psi,
                "breached": self.breached, "reasons": self.reasons,
                "features": [f.to_json() for f in self.features]}


class DriftMonitor:
    """Accumulates live sketches and scores; ``evaluate()`` produces a
    ``DriftReport`` and exports ``drift.*`` gauges/counters/events.

    Thread-safe: serving batch observers feed it concurrently with the
    controller's ``evaluate()`` calls."""

    def __init__(self, baselines: Optional[ModelBaselines],
                 raw_features: Sequence = (), *,
                 registry: Optional[MetricsRegistry] = None,
                 psi_threshold: float = 0.25,
                 score_psi_threshold: float = 0.25,
                 fill_delta_threshold: float = 0.2,
                 min_rows: int = 50, bins: int = 10):
        # 10 fixed bins: finer binnings inflate PSI on small live windows
        # (empty tail bins hit the epsilon clip and each contributes a
        # spurious ~eps*log term)
        self.registry = registry if registry is not None else MetricsRegistry()
        self.psi_threshold = float(psi_threshold)
        self.score_psi_threshold = float(score_psi_threshold)
        self.fill_delta_threshold = float(fill_delta_threshold)
        self.min_rows = int(min_rows)
        self.bins = int(bins)
        self.last_report: Optional[DriftReport] = None
        self._lock = threading.Lock()
        self._set_baselines(baselines, raw_features)

    @classmethod
    def for_model(cls, model, **kw) -> Optional["DriftMonitor"]:
        """Monitor for a loaded ``WorkflowModel``; ``None`` (drift disabled,
        recorded as a degradation) when its bundle carries no baselines."""
        baselines = getattr(model, "baselines", None)
        if baselines is None:
            from ..resilience import record_failure
            record_failure(
                "drift", "degraded",
                "model bundle has no baselines.json (pre-lifecycle build); "
                "drift monitoring disabled", point="checkpoint.load")
            return None
        raw = [f for f in model.raw_features if not f.is_response]
        return cls(baselines, raw, **kw)

    def _set_baselines(self, baselines: Optional[ModelBaselines],
                       raw_features: Sequence) -> None:
        self.baselines = baselines
        self.raw_features = list(raw_features)
        self.enabled = baselines is not None
        max_bins = baselines.max_bins if baselines is not None else 64
        self._live: Dict[Tuple[str, Optional[str]], FeatureSketch] = {}
        self._score_hist = StreamingHistogram(max_bins)
        self._rows = 0

    # -- observation -------------------------------------------------------
    def observe_batch(self, batch) -> None:
        """Accumulate a raw ``ColumnBatch`` from the live feed (the same
        sketch/merge path training uses, so live and baseline distributions
        are directly comparable)."""
        if not self.enabled or len(batch) == 0:
            return
        sketches = compute_sketches(self.raw_features, batch,
                                    max_bins=self.baselines.max_bins,
                                    text_bins=self.baselines.text_bins)
        with self._lock:
            self._live = merge_sketches(self._live, sketches)
            self._rows += len(batch)

    def observe_records(self, records: List[Dict[str, Any]]) -> None:
        """Accumulate raw serving records (the engine observer path)."""
        if not self.enabled or not records:
            return
        from ..serving.engine import records_to_batch
        self.observe_batch(records_to_batch(self.raw_features, records))

    def observe_scores(self, values) -> None:
        if not self.enabled:
            return
        arr = np.asarray(values, dtype=np.float64)
        with self._lock:
            self._score_hist.update_all(arr)

    def observe_results(self, results: List[Dict[str, Any]]) -> None:
        """Pull score values out of serving result rows (the Prediction
        column serializes as a dict of named values)."""
        if not self.enabled or self.baselines.score_feature is None:
            return
        vals = []
        for r in results:
            d = r.get(self.baselines.score_feature) if isinstance(r, dict) \
                else None
            if isinstance(d, dict):
                v = d.get(self.baselines.score_field, d.get("prediction"))
                if v is not None:
                    vals.append(float(np.asarray(v).reshape(-1)[0]))
        if vals:
            self.observe_scores(vals)

    def observe_serving(self, records: List[Dict[str, Any]],
                        results: List[Dict[str, Any]]) -> None:
        """ScoringEngine batch-observer entry point."""
        self.observe_records(records)
        self.observe_results(results)

    def observe_columnar(self, batch, result_arrays) -> None:
        """ScoringEngine column-observer entry point: the raw
        ``ColumnBatch`` feeds the same sketch path ``observe_batch`` uses
        (no per-record dict materialization), and the score stream comes
        straight out of the packed result arrays
        (``{name: (values, present_mask)}``)."""
        if not self.enabled:
            return
        self.observe_batch(batch)
        sf = self.baselines.score_feature
        if sf is None or not result_arrays:
            return
        entry = result_arrays.get(f"{sf}.{self.baselines.score_field}")
        if entry is None:
            entry = result_arrays.get(f"{sf}.prediction")
        if entry is None:
            return
        vals, mask = entry
        arr = np.asarray(vals, dtype=np.float64).reshape(-1)
        if mask is not None:
            arr = arr[np.asarray(mask, dtype=bool).reshape(-1)]
        arr = arr[np.isfinite(arr)]
        if arr.size:
            self.observe_scores(arr)

    @property
    def rows_observed(self) -> int:
        return self._rows

    # -- evaluation --------------------------------------------------------
    def evaluate(self) -> DriftReport:
        """Compare the accumulated window against the baselines."""
        with span("drift.evaluate", rows=self._rows):
            with self._lock:
                report = self._evaluate_locked()
        g = self.registry.gauge
        for f in report.features:
            lbl = _label(f.name, f.key)
            g(f"drift.psi.{lbl}").set(f.psi)
            g(f"drift.fill_delta.{lbl}").set(f.fill_delta)
        g("drift.score_psi").set(report.score_psi)
        g("drift.rows_observed").set(report.rows)
        self.registry.counter("drift.evaluations_total").inc()
        if report.breached:
            self.registry.counter("drift.breaches_total").inc()
            for f in report.features:
                if f.breached:
                    event("drift.breach", feature=_label(f.name, f.key),
                          psi=f.psi, fill_delta=f.fill_delta,
                          reasons="; ".join(f.reasons))
            if report.score_psi > self.score_psi_threshold and \
                    report.score_rows >= self.min_rows:
                event("drift.breach", feature="__score__",
                      psi=report.score_psi)
        self.last_report = report
        return report

    def _evaluate_locked(self) -> DriftReport:
        if not self.enabled:
            return DriftReport(ready=False, rows=0, score_rows=0,
                               score_psi=0.0)
        rows = self._rows
        ready = rows >= self.min_rows
        feats: List[FeatureDriftStat] = []
        reasons: List[str] = []
        for (name, key), base in sorted(self.baselines.features.items(),
                                        key=lambda kv: (kv[0][0],
                                                        kv[0][1] or "")):
            live = self._live.get((name, key))
            if live is None or live.count == 0:
                continue
            fill_delta = abs(base.fill_rate - live.fill_rate)
            if base.histogram is not None or live.histogram is not None:
                bh = base.histogram or StreamingHistogram()
                lh = live.histogram or StreamingHistogram()
                lo, hi = _shared_range(bh, lh)
                p = bh.to_fixed_bins(self.bins, lo, hi)
                q = lh.to_fixed_bins(self.bins, lo, hi)
            else:
                p = np.asarray(base.text_counts if base.text_counts is not None
                               else [], dtype=np.float64)
                q = np.asarray(live.text_counts if live.text_counts is not None
                               else [], dtype=np.float64)
            psi_v = psi(p, q)
            js = FeatureDistribution(
                name, key=key, count=base.count, nulls=base.nulls,
                distribution=np.asarray(p, dtype=np.float64)).js_divergence(
                FeatureDistribution(
                    name, key=key, count=live.count, nulls=live.nulls,
                    distribution=np.asarray(q, dtype=np.float64)))
            freasons: List[str] = []
            if ready:
                if psi_v > self.psi_threshold:
                    freasons.append(
                        f"{_label(name, key)}: PSI {psi_v:.3f} > "
                        f"{self.psi_threshold}")
                if fill_delta > self.fill_delta_threshold:
                    freasons.append(
                        f"{_label(name, key)}: fill-rate delta "
                        f"{fill_delta:.3f} > {self.fill_delta_threshold}")
            feats.append(FeatureDriftStat(
                name=name, key=key, psi=psi_v, js=js,
                fill_rate=live.fill_rate, baseline_fill_rate=base.fill_rate,
                fill_delta=fill_delta, rows=live.count, reasons=freasons))
            reasons.extend(freasons)
        score_psi = 0.0
        score_rows = int(self._score_hist.total)
        if self.baselines.score_histogram is not None and score_rows > 0:
            bh, lh = self.baselines.score_histogram, self._score_hist
            lo, hi = _shared_range(bh, lh)
            score_psi = psi(bh.to_fixed_bins(self.bins, lo, hi),
                            lh.to_fixed_bins(self.bins, lo, hi))
            if score_rows >= self.min_rows and \
                    score_psi > self.score_psi_threshold:
                reasons.append(f"score distribution: PSI {score_psi:.3f} > "
                               f"{self.score_psi_threshold}")
        return DriftReport(ready=ready, rows=rows, score_rows=score_rows,
                           score_psi=score_psi, features=feats,
                           reasons=reasons)

    # -- lifecycle ---------------------------------------------------------
    def reset(self) -> None:
        """Start a fresh observation window (baselines unchanged)."""
        with self._lock:
            self._live = {}
            self._score_hist = StreamingHistogram(
                self.baselines.max_bins if self.baselines is not None else 64)
            self._rows = 0

    def rebase(self, baselines: Optional[ModelBaselines],
               raw_features: Optional[Sequence] = None) -> None:
        """Swap in a newly-promoted model's baselines and reset the window.
        ``None`` disables the monitor (promoted bundle without baselines)."""
        with self._lock:
            self._set_baselines(
                baselines,
                raw_features if raw_features is not None
                else self.raw_features)
        if baselines is None:
            from ..resilience import record_failure
            record_failure(
                "drift", "degraded",
                "promoted bundle has no baselines.json; drift monitoring "
                "disabled until the next promotion", point="serving.reload")

    def rebase_to_model(self, model) -> None:
        self.rebase(getattr(model, "baselines", None),
                    [f for f in model.raw_features if not f.is_response])
