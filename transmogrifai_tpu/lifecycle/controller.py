"""LifecycleController — policy-gated retrain and holdout-gated promotion.

Closes the train → monitor → retrain → promote loop:

* pluggable retrain policies (``DriftThresholdPolicy`` on a
  ``DriftMonitor`` breach, ``ScheduledIntervalPolicy``, ``ManualPolicy``),
* retrains under ``preemption_guard`` with the selector sweep
  checkpointed to ``<root>/lifecycle/sweep`` — a SIGTERM (or injected
  preemption) mid-sweep leaves a resumable checkpoint and the NEXT
  retrain replays completed candidates instead of refitting them,
* warm-starts from the incumbent loaded via ``checkpoint.find_latest_valid``
  (``Workflow.with_model_stages`` reuses matching fitted stages),
* promotes the candidate only when it beats — or ties within
  ``tolerance`` — the incumbent's holdout metric; winners become a new
  ``ckpt-NNNNNN`` bundle under the serving root and trigger
  ``ScoringEngine.reload_now()`` (atomic hot swap); losers are kept under
  ``<root>/lifecycle/rejected/`` with a ``REJECTED.json`` marker and a
  FailureLog entry so an operator can audit why a retrain didn't ship.

Injection points ``lifecycle.retrain`` / ``lifecycle.promote`` let the
chaos harness kill the loop at either boundary; in both cases the
incumbent keeps serving.
"""

from __future__ import annotations

import os
import shutil
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence

from ..checkpoint import (TrainingPreempted, bundle_version,
                          find_latest_valid, next_version_dir,
                          preemption_guard, write_json_atomic)
from ..resilience import maybe_inject, record_failure
from ..telemetry import (REGISTRY, MetricsRegistry, current_trace_context,
                         event, span)
from .drift import DriftMonitor, DriftReport

SWEEP_SUBDIR = os.path.join("lifecycle", "sweep")
REJECTED_SUBDIR = os.path.join("lifecycle", "rejected")
REJECTED_MARKER = "REJECTED.json"


# -- retrain policies --------------------------------------------------------
class RetrainPolicy:
    """Decides whether a retrain should fire; returns a human-readable
    reason string, or ``None`` to stay put."""

    name = "policy"

    def should_retrain(self, report: Optional[DriftReport],
                       state: "LifecycleState") -> Optional[str]:
        raise NotImplementedError


class DriftThresholdPolicy(RetrainPolicy):
    """Fire when the drift monitor reports a breach (optionally rate-limited
    so a persistently-drifted feed can't retrain in a tight loop)."""

    name = "drift"

    def __init__(self, min_interval_s: float = 0.0):
        self.min_interval_s = float(min_interval_s)

    def should_retrain(self, report, state):
        if report is None or not report.breached:
            return None
        if self.min_interval_s and state.last_retrain_s is not None and \
                time.time() - state.last_retrain_s < self.min_interval_s:
            return None
        return "drift breach: " + "; ".join(report.reasons[:3])


class ScheduledIntervalPolicy(RetrainPolicy):
    """Fire every ``interval_s`` seconds regardless of drift."""

    name = "interval"

    def __init__(self, interval_s: float, time_fn: Callable[[], float] = time.time):
        self.interval_s = float(interval_s)
        self.time_fn = time_fn
        self._anchor: Optional[float] = None

    def should_retrain(self, report, state):
        now = self.time_fn()
        if self._anchor is None:
            self._anchor = now
        ref = state.last_retrain_s if state.last_retrain_s is not None \
            else self._anchor
        if now - ref >= self.interval_s:
            return f"scheduled retrain (interval {self.interval_s:g}s)"
        return None


class ManualPolicy(RetrainPolicy):
    """Fire once per explicit ``trigger()`` call (operator-driven)."""

    name = "manual"

    def __init__(self):
        self._pending: Optional[str] = None

    def trigger(self, reason: str = "manual trigger") -> None:
        self._pending = reason

    def should_retrain(self, report, state):
        reason, self._pending = self._pending, None
        return reason


# -- controller --------------------------------------------------------------
@dataclass
class LifecycleOutcome:
    """What one retrain attempt did."""

    status: str                      # promoted|rejected|preempted|failed
    reason: str = ""
    policy: str = ""
    metric_name: str = ""
    candidate_metric: Optional[float] = None
    incumbent_metric: Optional[float] = None
    candidate_path: Optional[str] = None
    bundle_version: Optional[str] = None
    resume_from: Optional[str] = None
    swapped: bool = False
    error: str = ""
    train_failures: Dict[str, int] = field(default_factory=dict)

    def to_json(self) -> Dict[str, Any]:
        return {"status": self.status, "reason": self.reason,
                "policy": self.policy, "metricName": self.metric_name,
                "candidateMetric": self.candidate_metric,
                "incumbentMetric": self.incumbent_metric,
                "candidatePath": self.candidate_path,
                "bundleVersion": self.bundle_version,
                "resumeFrom": self.resume_from, "swapped": self.swapped,
                "error": self.error, "trainFailures": self.train_failures}


@dataclass
class LifecycleState:
    retrains_total: int = 0
    promotions_total: int = 0
    rejections_total: int = 0
    preemptions_total: int = 0
    failed_retrains_total: int = 0
    last_retrain_s: Optional[float] = None
    last_outcome: Optional[LifecycleOutcome] = None

    def to_json(self) -> Dict[str, Any]:
        return {"retrains": self.retrains_total,
                "promotions": self.promotions_total,
                "rejections": self.rejections_total,
                "preemptions": self.preemptions_total,
                "failedRetrains": self.failed_retrains_total,
                "lastOutcome": (self.last_outcome.to_json()
                                if self.last_outcome else None)}


class LifecycleController:
    """See module docstring.

    ``workflow_factory`` builds (or returns) the ``Workflow`` to retrain
    with — its reader must point at the CURRENT training source, so a
    retrain fits on post-shift data.  ``holdout_records`` (raw dicts) or
    ``holdout_reader`` supplies labeled evaluation data for the gate."""

    def __init__(self, workflow_factory: Callable[[], Any],
                 checkpoint_root: str, evaluator, *,
                 holdout_records: Optional[List[Dict[str, Any]]] = None,
                 holdout_reader=None,
                 monitor: Optional[DriftMonitor] = None,
                 policies: Sequence[RetrainPolicy] = (),
                 engine=None, tolerance: float = 0.0,
                 warm_start: bool = True,
                 registry: Optional[MetricsRegistry] = None):
        if holdout_records is None and holdout_reader is None:
            raise ValueError("LifecycleController needs holdout_records or "
                             "holdout_reader for the promotion gate")
        self.workflow_factory = workflow_factory
        self.root = checkpoint_root
        self.evaluator = evaluator
        self.holdout_records = holdout_records
        self.holdout_reader = holdout_reader
        self.monitor = monitor
        self.policies = list(policies)
        self.engine = engine
        self.tolerance = float(tolerance)
        self.warm_start = bool(warm_start)
        self.registry = registry if registry is not None else REGISTRY
        self.state = LifecycleState()

    # -- evaluation helpers ------------------------------------------------
    def _holdout_batch(self, model):
        if self.holdout_reader is not None:
            return self.holdout_reader.generate_batch(model.raw_features)
        from ..readers import DataReader
        return DataReader(records=self.holdout_records).generate_batch(
            model.raw_features)

    def _holdout_metric(self, model) -> float:
        metrics = model.evaluate(self.evaluator,
                                 batch=self._holdout_batch(model))
        return float(metrics[self.evaluator.default_metric])

    def _load_incumbent(self):
        """(model, bundle_path) of the newest valid version, or (None, None)
        for a fresh root — the first promotion then ships unopposed."""
        from ..workflow import WorkflowModel
        try:
            path = find_latest_valid(self.root)
            return WorkflowModel.load(path), path
        except Exception as e:  # noqa: BLE001 — empty/corrupt root is fine
            record_failure("lifecycle", "skipped", e, point="checkpoint.load",
                           detail="no incumbent; candidate ships if it "
                                  "clears the holdout")
            return None, None

    # -- the loop ----------------------------------------------------------
    def run_once(self) -> Optional[LifecycleOutcome]:
        """One control iteration: evaluate drift, poll policies in order,
        retrain on the first that fires.  ``None`` when nothing fired."""
        report = self.monitor.evaluate() if self.monitor is not None else None
        for policy in self.policies:
            reason = policy.should_retrain(report, self.state)
            if reason:
                return self.retrain_and_promote(reason, policy=policy.name)
        return None

    def retrain_and_promote(self, reason: str,
                            policy: str = "manual") -> LifecycleOutcome:
        self.state.retrains_total += 1
        self.state.last_retrain_s = time.time()
        self.registry.counter("lifecycle.retrains_total").inc()
        sweep_dir = os.path.join(self.root, SWEEP_SUBDIR)
        # nest the retrain under the triggering request/monitor span (or the
        # TRANSMOGRIFAI_TRACEPARENT a parent process exported), so lifecycle
        # work shows up on the same distributed trace as its cause
        parent_ctx = current_trace_context()
        with span("lifecycle.retrain",
                  ctx=parent_ctx.child() if parent_ctx else None,
                  reason=reason, policy=policy,
                  attempt=self.state.retrains_total):
            event("lifecycle.retrain", reason=reason, policy=policy)
            outcome = self._retrain_inner(reason, policy, sweep_dir)
        self.state.last_outcome = outcome
        return outcome

    def _retrain_inner(self, reason: str, policy: str,
                       sweep_dir: str) -> LifecycleOutcome:
        try:
            maybe_inject("lifecycle.retrain",
                         key=str(self.state.retrains_total))
        except Exception as e:  # noqa: BLE001 — injected chaos
            return self._failed(reason, policy, e, "lifecycle.retrain")
        incumbent, incumbent_path = self._load_incumbent()
        wf = self.workflow_factory()
        if self.warm_start and incumbent is not None:
            wf.with_model_stages(incumbent)
        try:
            with preemption_guard("lifecycle"):
                candidate = wf.train(resume_from=sweep_dir)
        except TrainingPreempted as e:
            self.state.preemptions_total += 1
            self.registry.counter("lifecycle.preemptions_total").inc()
            resume = getattr(e, "resume_from", None) or sweep_dir
            record_failure("lifecycle", "preempted", e,
                           point="lifecycle.retrain", resume_from=resume)
            return LifecycleOutcome("preempted", reason=reason, policy=policy,
                                    resume_from=resume, error=str(e))
        except Exception as e:  # noqa: BLE001 — a failed retrain must not
            #                     take the incumbent down with it
            return self._failed(reason, policy, e, "lifecycle.retrain")
        outcome = self._promote_if_better(candidate, incumbent, reason,
                                          policy)
        flog = getattr(candidate, "failure_log", None)
        if flog is not None:
            outcome.train_failures = flog.summary()
        if outcome.status in ("promoted", "rejected"):
            # the sweep served its purpose; keeping it would make the NEXT
            # retrain replay THIS sweep's fits (candidate signatures don't
            # hash the training data) instead of fitting fresh data
            shutil.rmtree(sweep_dir, ignore_errors=True)
        # a standing lifecycle host retrains indefinitely — each cycle
        # publishes into the compiled-program registry and appends to the
        # persistent compile cache, so each cycle also re-enforces both
        # byte budgets (aot_registry GC: LRU-by-atime, stale-ABI first)
        try:
            from ..aot_registry import enforce_budget, gc_compile_cache
            enforce_budget()
            gc_compile_cache()
        except Exception as e:  # noqa: BLE001 — GC must not fail a retrain
            record_failure("lifecycle", "swallowed", e,
                           point="lifecycle.registry_gc")
        return outcome

    def _failed(self, reason: str, policy: str, e: Exception,
                point: str) -> LifecycleOutcome:
        self.state.failed_retrains_total += 1
        self.registry.counter("lifecycle.failed_retrains_total").inc()
        record_failure("lifecycle", "skipped", e, point=point)
        return LifecycleOutcome("failed", reason=reason, policy=policy,
                                error=f"{type(e).__name__}: {e}")

    def _promote_if_better(self, candidate, incumbent, reason: str,
                           policy: str) -> LifecycleOutcome:
        metric_name = self.evaluator.default_metric
        larger = getattr(self.evaluator, "is_larger_better", True)
        with span("lifecycle.promote", metric=metric_name):
            cand_m = self._holdout_metric(candidate)
            inc_m = (self._holdout_metric(incumbent)
                     if incumbent is not None else None)
            if inc_m is None:
                wins = True
            elif larger:
                wins = cand_m >= inc_m - self.tolerance
            else:
                wins = cand_m <= inc_m + self.tolerance
            try:
                maybe_inject("lifecycle.promote",
                             key=str(self.state.retrains_total))
            except Exception as e:  # noqa: BLE001 — injected chaos: die
                #                     right before the commit; incumbent
                #                     keeps serving
                return self._failed(reason, policy, e, "lifecycle.promote")
            if wins:
                return self._promote(candidate, reason, policy, metric_name,
                                     cand_m, inc_m)
            return self._reject(candidate, reason, policy, metric_name,
                                cand_m, inc_m)

    def _promote(self, candidate, reason, policy, metric_name,
                 cand_m, inc_m) -> LifecycleOutcome:
        path = next_version_dir(self.root)
        candidate.save(path)
        version = bundle_version(path)
        self.state.promotions_total += 1
        self.registry.counter("lifecycle.promotions_total").inc()
        record_failure("lifecycle", "promoted", None,
                       point="lifecycle.promote", bundle=path,
                       metric=metric_name, candidate_metric=cand_m,
                       incumbent_metric=inc_m, reason=reason)
        event("lifecycle.promoted", bundle=version, metric=metric_name,
              candidate_metric=cand_m, incumbent_metric=inc_m)
        swapped = False
        if self.engine is not None:
            # respect the serving reload breaker: when repeated bad bundles
            # opened it, the promotion is committed on disk but the hot swap
            # is deferred to the engine's watcher (which probes the breaker)
            breaker = getattr(getattr(self.engine, "overload", None),
                              "reload_breaker", None)
            if breaker is not None and \
                    breaker.current_state() == breaker.OPEN:
                record_failure(
                    "lifecycle", "skipped",
                    f"serving reload breaker open; hot swap of {version} "
                    f"deferred (next probe in {breaker.retry_after_s():.1f}s)",
                    point="lifecycle.promote", bundle=path)
            else:
                swapped = bool(self.engine.reload_now())
        elif self.monitor is not None:
            # no engine to rebase it on swap — rebase directly
            from .baselines import load_baselines
            self.monitor.rebase(load_baselines(path),
                                [f for f in candidate.raw_features
                                 if not f.is_response])
        return LifecycleOutcome("promoted", reason=reason, policy=policy,
                                metric_name=metric_name,
                                candidate_metric=cand_m,
                                incumbent_metric=inc_m, candidate_path=path,
                                bundle_version=version, swapped=swapped)

    def _reject(self, candidate, reason, policy, metric_name,
                cand_m, inc_m) -> LifecycleOutcome:
        # the loser is preserved for audit under <root>/lifecycle/rejected/
        # ("lifecycle" is not a bundle dir, so find_latest_valid never
        # serves it); the marker is written AFTER the atomic save —
        # verify_bundle ignores files outside the manifest
        path = next_version_dir(os.path.join(self.root, REJECTED_SUBDIR))
        candidate.save(path)
        write_json_atomic(os.path.join(path, REJECTED_MARKER),
                          {"reason": reason, "metric": metric_name,
                           "candidateMetric": cand_m,
                           "incumbentMetric": inc_m,
                           "tolerance": self.tolerance,
                           "rejectedAt": time.time()})
        self.state.rejections_total += 1
        self.registry.counter("lifecycle.rejections_total").inc()
        record_failure("lifecycle", "rejected",
                       f"candidate {metric_name}={cand_m:.4f} did not beat "
                       f"incumbent {metric_name}={inc_m:.4f} "
                       f"(tolerance {self.tolerance})",
                       point="lifecycle.promote", bundle=path)
        event("lifecycle.rejected", bundle=path, metric=metric_name,
              candidate_metric=cand_m, incumbent_metric=inc_m)
        return LifecycleOutcome("rejected", reason=reason, policy=policy,
                                metric_name=metric_name,
                                candidate_metric=cand_m,
                                incumbent_metric=inc_m, candidate_path=path)


# --------------------------------------------------------------------------
# multi-tenant retrain prioritisation
# --------------------------------------------------------------------------

def rank_tenants_for_retrain(registry, *, min_rows: int = 1
                             ) -> List[Dict[str, Any]]:
    """Order a ``serving.tenants.TenantRegistry``'s tenants by
    traffic-weighted drift severity: the tenant whose drift hurts the most
    *users* retrains first.

    Per ACTIVE tenant with an attached drift monitor (registry built with
    ``drift=True``) that has observed at least ``min_rows`` rows, the
    score is ``traffic_share * (1 + drift_psi)`` where ``drift_psi`` is
    the worst of the score PSI and any per-feature PSI.  Tenants whose
    window actually *breached* sort above all non-breached tenants
    regardless of score — a breach is a retrain trigger, the weight only
    orders the queue.  Cold, quarantined and monitor-less tenants are
    excluded (nothing to compare; quarantine is a bundle problem, not a
    drift problem)."""
    weights = registry.traffic_weights()
    total = sum(max(0, w) for w in weights.values()) or 1
    ranked: List[Dict[str, Any]] = []
    for tenant in registry.tenants():
        engine = registry.peek_engine(tenant)
        monitor = getattr(engine, "drift_monitor", None) if engine else None
        if monitor is None or monitor.rows_observed < min_rows:
            continue
        try:
            report = monitor.evaluate()
        except Exception as e:  # noqa: BLE001 — one tenant's broken
            #                     monitor must not stop the ranking
            record_failure("lifecycle", "swallowed", e,
                           point="lifecycle.tenants", tenant=tenant)
            continue
        psi = max([report.score_psi]
                  + [f.psi for f in report.features if f.psi == f.psi])
        share = max(0, weights.get(tenant, 0)) / total
        ranked.append({"tenant": tenant, "breached": report.breached,
                       "trafficShare": round(share, 6),
                       "driftPsi": round(float(psi), 6),
                       "rows": report.rows,
                       "priority": round(share * (1.0 + float(psi)), 6),
                       "reasons": list(report.reasons)})
    ranked.sort(key=lambda r: (not r["breached"], -r["priority"],
                               r["tenant"]))
    return ranked
