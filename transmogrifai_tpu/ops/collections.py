"""Set/list vectorizers (reference: core/.../stages/impl/feature/
{MultiPickListMapVectorizer for maps, OpSetVectorizer}.scala — the top-K pivot
over MultiPickList sets).
"""

from __future__ import annotations

from collections import Counter
from typing import Dict, List

import jax.numpy as jnp
import numpy as np

from .categorical import top_values_by_count
from ..columns import Column, ColumnBatch
from ..stages.base import Estimator, TransformerModel
from ..types import OPVector
from ..vector_meta import (NULL_INDICATOR, OTHER_INDICATOR, VectorColumnMeta,
                           VectorMeta)


class MultiPickListVectorizerModel(TransformerModel):
    out_kind = OPVector
    is_device_op = False

    def transform(self, batch: ColumnBatch) -> Column:
        outs = []
        for f in self.input_features:
            vocab: Dict[str, int] = self.fitted["vocabs"][f.name]
            sets = batch[f.name].values
            width = len(vocab) + (1 if self.get("track_other", True) else 0) \
                + (1 if self.get("track_nulls", True) else 0)
            block = np.zeros((len(sets), width), np.float32)
            other_col = len(vocab) if self.get("track_other", True) else None
            null_col = width - 1 if self.get("track_nulls", True) else None
            for i, s in enumerate(sets):
                if not s:
                    if null_col is not None:
                        block[i, null_col] = 1.0
                    continue
                for v in s:
                    j = vocab.get(v)
                    if j is not None:
                        block[i, j] = 1.0
                    elif other_col is not None:
                        block[i, other_col] = 1.0
            outs.append(block)
        arr = np.concatenate(outs, axis=1)
        return Column(OPVector, jnp.asarray(arr), meta=self.fitted["meta"])


class MultiPickListVectorizer(Estimator):
    """Top-K membership pivot of MultiPickList sets with OTHER + null slots."""

    out_kind = OPVector

    def __init__(self, top_k: int = 20, min_support: int = 10,
                 track_nulls: bool = True, track_other: bool = True, **params):
        super().__init__(top_k=top_k, min_support=min_support,
                         track_nulls=track_nulls, track_other=track_other,
                         **params)

    def fit(self, batch: ColumnBatch) -> TransformerModel:
        vocabs: Dict[str, Dict[str, int]] = {}
        cols_meta: List[VectorColumnMeta] = []
        for f in self.input_features:
            counts = Counter()
            for s in batch[f.name].values:
                for v in (s or ()):
                    counts[v] += 1
            top = top_values_by_count(counts, self.get("top_k"),
                                      self.get("min_support"))
            vocab = {v: i for i, v in enumerate(top)}
            vocabs[f.name] = vocab
            for v in top:
                cols_meta.append(VectorColumnMeta(
                    f.name, f.kind.__name__, indicator_value=v))
            if self.get("track_other", True):
                cols_meta.append(VectorColumnMeta(
                    f.name, f.kind.__name__, indicator_value=OTHER_INDICATOR))
            if self.get("track_nulls", True):
                cols_meta.append(VectorColumnMeta(
                    f.name, f.kind.__name__, indicator_value=NULL_INDICATOR))
        meta = VectorMeta(self.output_name(), cols_meta)
        return self._finalize_model(MultiPickListVectorizerModel(
            fitted={"vocabs": vocabs, "meta": meta}, **self.params))
