"""Bucketizers, calibrators and scalers (reference: core/.../stages/impl/
feature/NumericBucketizer.scala, DecisionTreeNumericBucketizer.scala:60,74,
DecisionTreeNumericMapBucketizer.scala, PercentileCalibrator.scala,
ScalerTransformer.scala, DescalerTransformer.scala and
impl/regression/IsotonicRegressionCalibrator.scala).

TPU design notes: bucketization is a ``searchsorted`` + one-hot — pure array
ops; the decision-tree bucketizer reuses the framework's own histogram tree
trainer (models/trees.fit_tree) on a single feature instead of spinning up a
Spark DecisionTreeClassifier; isotonic calibration is pool-adjacent-violators
on the sorted scores with linear interpolation at predict time, exactly
Spark's IsotonicRegressionModel contract.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from ..columns import Column, ColumnBatch
from ..stages.base import Estimator, Transformer, TransformerModel
from ..types import OPNumeric, OPVector, Real, RealNN
from ..vector_meta import NULL_INDICATOR, VectorColumnMeta, VectorMeta

# reference defaults (DecisionTreeNumericBucketizer.scala:293-300)
DT_BUCKETIZER_MAX_DEPTH = 5
DT_BUCKETIZER_MAX_BINS = 32
DT_BUCKETIZER_MIN_INSTANCES = 1
DT_BUCKETIZER_MIN_INFO_GAIN = 0.01
INVALID_INDICATOR = "OTHER"  # reference tracks invalid values under "OTHER"


def splits_to_bucket_labels(splits: Sequence[float],
                            inclusion: str = "Left") -> List[str]:
    """≙ NumericBucketizer.splitsToBucketLabels: human-readable range labels."""
    lo, hi = ("[", ")") if inclusion == "Left" else ("(", "]")
    return [f"{lo}{splits[i]}-{splits[i + 1]}{hi}"
            for i in range(len(splits) - 1)]


def bucketize_values(v: np.ndarray, mask: Optional[np.ndarray],
                     splits: np.ndarray, *, inclusion: str = "Left",
                     track_nulls: bool = True,
                     track_invalid: bool = False) -> np.ndarray:
    """One-hot bucket matrix for values ``v`` against ``splits`` (len B+1,
    usually bracketed by ±inf).  Columns: B buckets [+ invalid] [+ null].
    ≙ NumericBucketizer.bucketize."""
    v = np.asarray(v, dtype=np.float64)
    n = len(v)
    B = len(splits) - 1
    present = np.ones(n, bool) if mask is None else np.asarray(mask, bool)
    finite = np.isfinite(np.nan_to_num(v, nan=np.inf)) & ~np.isnan(v)
    side = "right" if inclusion == "Left" else "left"
    idx = np.searchsorted(splits, v, side=side) - 1
    valid = present & finite & (idx >= 0) & (idx < B)
    cols = B + (1 if track_invalid else 0) + (1 if track_nulls else 0)
    out = np.zeros((n, cols), np.float32)
    rows = np.flatnonzero(valid)
    out[rows, np.clip(idx[rows], 0, B - 1)] = 1.0
    c = B
    if track_invalid:
        out[present & ~valid, c] = 1.0
        c += 1
    if track_nulls:
        out[~present, c] = 1.0
    return out


def _bucket_meta(feature_name: str, kind_name: str, out_name: str,
                 labels: Sequence[str], track_nulls: bool,
                 track_invalid: bool) -> VectorMeta:
    cols = [VectorColumnMeta(feature_name, kind_name, indicator_value=lbl)
            for lbl in labels]
    if track_invalid:
        cols.append(VectorColumnMeta(feature_name, kind_name,
                                     indicator_value=INVALID_INDICATOR))
    if track_nulls:
        cols.append(VectorColumnMeta(feature_name, kind_name,
                                     indicator_value=NULL_INDICATOR))
    return VectorMeta(out_name, cols)


class NumericBucketizer(Transformer):
    """Fixed-split bucketization of a numeric feature into a one-hot vector
    (≙ NumericBucketizer.scala).  ``splits`` must be monotonically increasing;
    values outside the range are invalid (tracked if ``track_invalid``)."""

    in_kinds = (OPNumeric,)
    out_kind = OPVector
    is_device_op = False

    def __init__(self, splits: Sequence[float] = (-np.inf, 0.0, np.inf),
                 bucket_labels: Optional[Sequence[str]] = None,
                 split_inclusion: str = "Left", track_nulls: bool = True,
                 track_invalid: bool = False, **params):
        splits = [float(s) for s in splits]
        if sorted(splits) != splits or len(set(splits)) != len(splits):
            raise ValueError("splits must be strictly increasing")
        if len(splits) < 3:
            raise ValueError("at least 3 split points required")
        super().__init__(splits=splits, bucket_labels=list(bucket_labels or []),
                         split_inclusion=split_inclusion,
                         track_nulls=track_nulls, track_invalid=track_invalid,
                         **params)

    def transform(self, batch: ColumnBatch) -> Column:
        (f,) = self.input_features
        col = batch[f.name]
        splits = np.asarray(self.get("splits"), np.float64)
        labels = (self.get("bucket_labels")
                  or splits_to_bucket_labels(splits, self.get("split_inclusion")))
        out = bucketize_values(
            np.asarray(col.values, np.float64), col.mask, splits,
            inclusion=self.get("split_inclusion", "Left"),
            track_nulls=self.get("track_nulls", True),
            track_invalid=self.get("track_invalid", False))
        meta = _bucket_meta(f.name, f.kind.__name__, self.output_name(), labels,
                            self.get("track_nulls", True),
                            self.get("track_invalid", False))
        return Column(OPVector, out, meta=meta)


def tree_splits_for_feature(x: np.ndarray, y: np.ndarray, *,
                            max_depth: int = DT_BUCKETIZER_MAX_DEPTH,
                            max_bins: int = DT_BUCKETIZER_MAX_BINS,
                            min_instances: int = DT_BUCKETIZER_MIN_INSTANCES,
                            min_gain: float = DT_BUCKETIZER_MIN_INFO_GAIN
                            ) -> np.ndarray:
    """Split thresholds of a single-feature gini decision tree fit against the
    label — the reference's trick of using DecisionTreeClassifier.rootNode
    .splits as bucket boundaries (DecisionTreeNumericBucketizer.scala:253-275).
    Reuses the framework's histogram tree trainer."""
    from ..models.trees import bin_data, build_bin_splits, fit_tree

    if len(x) == 0:
        return np.asarray([], np.float64)
    X = np.asarray(x, np.float32)[:, None]
    classes, y_idx = np.unique(np.asarray(y), return_inverse=True)
    n_classes = max(len(classes), 2)
    splits = build_bin_splits(X, max_bins)
    B = bin_data(jnp.asarray(X), jnp.asarray(splits))
    yoh = np.zeros((len(x), n_classes), np.float32)
    yoh[np.arange(len(x)), y_idx] = 1.0
    stats = jnp.asarray(
        np.concatenate([np.ones((len(x), 1), np.float32), yoh], axis=1))
    tree = fit_tree(B, jnp.asarray(splits), stats,
                    jnp.ones((1,), jnp.float32) > 0, impurity="gini",
                    max_depth=max_depth, n_bins=max_bins,
                    min_instances=jnp.float32(min_instances),
                    min_gain=jnp.float32(min_gain), lam=jnp.float32(1.0))
    feat = np.asarray(tree.feature)
    thr = np.asarray(tree.threshold)
    used = np.unique(thr[(feat >= 0) & np.isfinite(thr)])
    return used.astype(np.float64)


class DecisionTreeNumericBucketizerModel(TransformerModel):
    out_kind = OPVector
    allow_label_as_input = True
    is_device_op = False

    def transform(self, batch: ColumnBatch) -> Column:
        f = self.input_features[1]
        col = batch[f.name]
        track_nulls = self.get("track_nulls", True)
        track_invalid = self.get("track_invalid", False)
        should_split = bool(self.fitted["should_split"])
        splits = np.asarray(self.fitted["splits"], np.float64)
        n = len(col)
        if should_split:
            out = bucketize_values(
                np.asarray(col.values, np.float64), col.mask, splits,
                inclusion="Right", track_nulls=track_nulls,
                track_invalid=track_invalid)
            labels = splits_to_bucket_labels(splits, "Right")
        else:
            # no usable splits: emit the null indicator only (reference emits
            # an empty vector + optional null tracking)
            present = (np.ones(n, bool) if col.mask is None
                       else np.asarray(col.mask, bool))
            out = ((~present).astype(np.float32)[:, None] if track_nulls
                   else np.zeros((n, 0), np.float32))
            labels = []
        meta = _bucket_meta(f.name, f.kind.__name__, self.output_name(),
                            labels, track_nulls,
                            should_split and track_invalid)
        return Column(OPVector, out, meta=meta)


class DecisionTreeNumericBucketizer(Estimator):
    """Smart bucketizer: buckets a numeric feature at the split points of a
    single-feature decision tree trained against the label
    (≙ DecisionTreeNumericBucketizer.scala:60,74).  Inputs (label: RealNN,
    feature: numeric)."""

    in_kinds = (RealNN, OPNumeric)
    out_kind = OPVector
    allow_label_as_input = True

    def __init__(self, max_depth: int = DT_BUCKETIZER_MAX_DEPTH,
                 max_bins: int = DT_BUCKETIZER_MAX_BINS,
                 min_instances_per_node: int = DT_BUCKETIZER_MIN_INSTANCES,
                 min_info_gain: float = DT_BUCKETIZER_MIN_INFO_GAIN,
                 track_nulls: bool = True, track_invalid: bool = True,
                 **params):
        super().__init__(max_depth=max_depth, max_bins=max_bins,
                         min_instances_per_node=min_instances_per_node,
                         min_info_gain=min_info_gain, track_nulls=track_nulls,
                         track_invalid=track_invalid, **params)

    def output_name(self) -> str:
        return f"{self.input_features[1].name}_dtBucketized_{self.uid[-6:]}"

    def _compute_splits(self, x: np.ndarray, mask: Optional[np.ndarray],
                        y: np.ndarray) -> Tuple[bool, np.ndarray]:
        present = np.ones(len(x), bool) if mask is None else np.asarray(mask, bool)
        present &= ~np.isnan(np.asarray(x, np.float64))
        inner = tree_splits_for_feature(
            np.asarray(x, np.float64)[present], np.asarray(y)[present],
            max_depth=int(self.get("max_depth", DT_BUCKETIZER_MAX_DEPTH)),
            max_bins=int(self.get("max_bins", DT_BUCKETIZER_MAX_BINS)),
            min_instances=int(self.get("min_instances_per_node", 1)),
            min_gain=float(self.get("min_info_gain", 0.01)))
        should_split = len(inner) > 0
        splits = (np.r_[-np.inf, inner, np.inf] if should_split
                  else np.asarray([], np.float64))
        return should_split, splits

    def fit(self, batch: ColumnBatch) -> DecisionTreeNumericBucketizerModel:
        label_f, f = self.input_features
        y = np.asarray(batch[label_f.name].values, np.float64)
        col = batch[f.name]
        should_split, splits = self._compute_splits(
            np.asarray(col.values, np.float64), col.mask, y)
        model = DecisionTreeNumericBucketizerModel(
            fitted={"should_split": should_split, "splits": splits},
            **self._params)
        return self._finalize_model(model)


class DecisionTreeNumericMapBucketizerModel(TransformerModel):
    out_kind = OPVector
    allow_label_as_input = True
    is_device_op = False

    def transform(self, batch: ColumnBatch) -> Column:
        f = self.input_features[1]
        maps = [v if isinstance(v, dict) else {} for v in batch[f.name].values]
        n = len(maps)
        track_nulls = self.get("track_nulls", True)
        track_invalid = self.get("track_invalid", False)
        blocks, cols_meta = [], []
        for k in self.fitted["keys"]:
            ks = self.fitted["splits_by_key"].get(k)
            vals = np.asarray([float(m[k]) if m.get(k) is not None else np.nan
                               for m in maps], np.float64)
            mask = ~np.isnan(vals)
            if ks is not None and len(ks):
                splits = np.asarray(ks, np.float64)
                blocks.append(bucketize_values(
                    vals, mask, splits, inclusion="Right",
                    track_nulls=track_nulls, track_invalid=track_invalid))
                labels = splits_to_bucket_labels(splits, "Right")
                cols_meta += [VectorColumnMeta(f.name, f.kind.__name__,
                                               grouping=k, indicator_value=lbl)
                              for lbl in labels]
                if track_invalid:
                    cols_meta.append(VectorColumnMeta(
                        f.name, f.kind.__name__, grouping=k,
                        indicator_value=INVALID_INDICATOR))
            else:
                blocks.append((~mask).astype(np.float32)[:, None]
                              if track_nulls else np.zeros((n, 0), np.float32))
            if track_nulls:
                if ks is not None and len(ks):
                    blocks.append((~mask).astype(np.float32)[:, None])
                cols_meta.append(VectorColumnMeta(
                    f.name, f.kind.__name__, grouping=k,
                    indicator_value=NULL_INDICATOR))
        out = (np.concatenate(blocks, axis=1) if blocks
               else np.zeros((n, 0), np.float32))
        return Column(OPVector, out,
                      meta=VectorMeta(self.output_name(), cols_meta))


class DecisionTreeNumericMapBucketizer(Estimator):
    """Per-key smart bucketization of a numeric map
    (≙ DecisionTreeNumericMapBucketizer.scala): each key's values are
    bucketized at its own label-driven tree splits."""

    in_kinds = (RealNN, None)
    out_kind = OPVector
    allow_label_as_input = True

    def __init__(self, max_depth: int = DT_BUCKETIZER_MAX_DEPTH,
                 max_bins: int = DT_BUCKETIZER_MAX_BINS,
                 min_instances_per_node: int = DT_BUCKETIZER_MIN_INSTANCES,
                 min_info_gain: float = DT_BUCKETIZER_MIN_INFO_GAIN,
                 track_nulls: bool = True, track_invalid: bool = False,
                 max_keys: int = 100, **params):
        super().__init__(max_depth=max_depth, max_bins=max_bins,
                         min_instances_per_node=min_instances_per_node,
                         min_info_gain=min_info_gain, track_nulls=track_nulls,
                         track_invalid=track_invalid, max_keys=max_keys,
                         **params)

    def fit(self, batch: ColumnBatch) -> DecisionTreeNumericMapBucketizerModel:
        label_f, f = self.input_features
        y = np.asarray(batch[label_f.name].values, np.float64)
        maps = [v if isinstance(v, dict) else {} for v in batch[f.name].values]
        keys: List[str] = sorted({k for m in maps for k in m}
                                 )[:int(self.get("max_keys", 100))]
        splits_by_key: Dict[str, np.ndarray] = {}
        for k in keys:
            vals = np.asarray([float(m[k]) if m.get(k) is not None else np.nan
                               for m in maps], np.float64)
            present = ~np.isnan(vals)
            inner = tree_splits_for_feature(
                vals[present], y[present],
                max_depth=int(self.get("max_depth", DT_BUCKETIZER_MAX_DEPTH)),
                max_bins=int(self.get("max_bins", DT_BUCKETIZER_MAX_BINS)),
                min_instances=int(self.get("min_instances_per_node", 1)),
                min_gain=float(self.get("min_info_gain", 0.01))
            ) if present.any() else np.asarray([])
            splits_by_key[k] = (np.r_[-np.inf, inner, np.inf]
                                if len(inner) else np.asarray([]))
        model = DecisionTreeNumericMapBucketizerModel(
            fitted={"keys": keys, "splits_by_key": splits_by_key},
            **self._params)
        return self._finalize_model(model)


class PercentileCalibratorModel(TransformerModel):
    out_kind = RealNN
    is_device_op = False

    def transform(self, batch: ColumnBatch) -> Column:
        (f,) = self.input_features
        v = np.asarray(batch[f.name].values, np.float64)
        splits = np.asarray(self.fitted["splits"], np.float64)
        expected = int(self.get("expected_num_buckets", 100))
        actual = len(splits)
        idx = np.searchsorted(splits, v, side="left")
        if actual >= expected:
            out = np.maximum(idx - 1, 0)
        else:
            # scale the sparser actual bucket range onto [0, expected-1]
            # (≙ PercentileCalibratorModel.scale)
            old_max, new_max = max(actual - 1, 1), expected - 1
            out = np.round(idx * (new_max / old_max))
        return Column(RealNN, np.clip(out, 0, expected - 1).astype(np.float32))


class PercentileCalibrator(Estimator):
    """Calibrate a real-valued score into [0, expected_num_buckets-1]
    percentile ranks (≙ PercentileCalibrator.scala; QuantileDiscretizer with
    relativeError=0)."""

    in_kinds = (RealNN,)
    out_kind = RealNN

    def __init__(self, expected_num_buckets: int = 100, **params):
        super().__init__(expected_num_buckets=expected_num_buckets, **params)

    def fit(self, batch: ColumnBatch) -> PercentileCalibratorModel:
        (f,) = self.input_features
        v = np.asarray(batch[f.name].values, np.float64)
        buckets = int(self.get("expected_num_buckets", 100))
        qs = np.linspace(0.0, 1.0, buckets + 1)[1:-1]
        inner = np.unique(np.quantile(v, qs)) if len(v) else np.asarray([])
        splits = np.r_[-np.inf, inner, np.inf]
        model = PercentileCalibratorModel(
            fitted={"splits": splits, "actual_num_buckets": len(splits)},
            **self._params)
        model.metadata["origSplits"] = [float(s) for s in splits]
        return self._finalize_model(model)


# ---------------------------------------------------------------------------
# scaler / descaler
# ---------------------------------------------------------------------------

_SCALERS: Dict[str, Tuple[Any, Any]] = {
    # scaling_type -> (forward, inverse); args taken from stage params
    "Linear": (lambda v, a: a.get("slope", 1.0) * v + a.get("intercept", 0.0),
               lambda v, a: (v - a.get("intercept", 0.0)) / a.get("slope", 1.0)),
    "Logarithmic": (lambda v, a: np.log(v), lambda v, a: np.exp(v)),
}


class ScalerTransformer(Transformer):
    """Apply an invertible scaling function, recording its metadata so a
    DescalerTransformer can undo it (≙ ScalerTransformer.scala, Scaler.scala:
    LinearScaler/LogScaler)."""

    in_kinds = (Real,)
    out_kind = Real
    is_device_op = False

    def __init__(self, scaling_type: str = "Linear",
                 scaling_args: Optional[Dict[str, float]] = None, **params):
        if scaling_type not in _SCALERS:
            raise ValueError(f"unknown scaling type {scaling_type!r}")
        scaling_args = dict(scaling_args or {})
        if scaling_type == "Linear" and scaling_args.get("slope", 1.0) == 0.0:
            raise ValueError("LinearScaler must have a non-zero slope")
        super().__init__(scaling_type=scaling_type, scaling_args=scaling_args,
                         **params)

    def transform(self, batch: ColumnBatch) -> Column:
        (f,) = self.input_features
        col = batch[f.name]
        fwd, _ = _SCALERS[self.get("scaling_type")]
        v = fwd(np.asarray(col.values, np.float64), self.get("scaling_args"))
        return Column(Real, v.astype(np.float32), mask=col.mask)


class DescalerTransformer(Transformer):
    """Invert the scaling applied by a ScalerTransformer: inputs (value to
    descale, scaled feature whose origin stage carries the scaler metadata)
    (≙ DescalerTransformer.scala)."""

    in_kinds = (Real, Real)
    out_kind = Real
    is_device_op = False

    def _find_scaler(self):
        origin = self.input_features[1].origin_stage
        if not isinstance(origin, ScalerTransformer):
            raise ValueError(
                "DescalerTransformer input 2 must be produced by a "
                f"ScalerTransformer, got {type(origin).__name__}")
        return origin

    def transform(self, batch: ColumnBatch) -> Column:
        scaler = self._find_scaler()
        col = batch[self.input_features[0].name]
        _, inv = _SCALERS[scaler.get("scaling_type")]
        v = inv(np.asarray(col.values, np.float64), scaler.get("scaling_args"))
        return Column(Real, v.astype(np.float32), mask=col.mask)


# ---------------------------------------------------------------------------
# isotonic calibration
# ---------------------------------------------------------------------------

def pav_fit(x: np.ndarray, y: np.ndarray, w: Optional[np.ndarray] = None,
            increasing: bool = True) -> Tuple[np.ndarray, np.ndarray]:
    """Pool-adjacent-violators on (x, y) → (boundaries, values) of the fitted
    step function (≙ Spark ml IsotonicRegression; predictions interpolate
    linearly between boundaries)."""
    x = np.asarray(x, np.float64)
    y = np.asarray(y, np.float64)
    w = np.ones_like(y) if w is None else np.asarray(w, np.float64)
    order = np.argsort(x, kind="mergesort")
    xs, ys, ws = x[order], y[order], w[order]
    if not increasing:
        ys = -ys
    # block-merge stack: each block holds (weighted mean, weight, start idx)
    means: List[float] = []
    weights: List[float] = []
    starts: List[int] = []
    for i in range(len(ys)):
        means.append(float(ys[i]))
        weights.append(float(ws[i]))
        starts.append(i)
        while len(means) > 1 and means[-2] >= means[-1]:
            m2, w2 = means.pop(), weights.pop()
            starts.pop()
            means[-1] = (means[-1] * weights[-1] + m2 * w2) / (weights[-1] + w2)
            weights[-1] += w2
    bounds, vals = [], []
    starts.append(len(ys))
    for bi in range(len(means)):
        lo, hi = starts[bi], starts[bi + 1] - 1
        v = means[bi] if increasing else -means[bi]
        bounds.append(xs[lo])
        vals.append(v)
        if xs[hi] != xs[lo]:
            bounds.append(xs[hi])
            vals.append(v)
    return np.asarray(bounds), np.asarray(vals)


class IsotonicRegressionCalibratorModel(TransformerModel):
    out_kind = RealNN
    allow_label_as_input = True
    is_device_op = False

    def transform(self, batch: ColumnBatch) -> Column:
        f = self.input_features[1]
        v = np.asarray(batch[f.name].values, np.float64)
        out = np.interp(v, np.asarray(self.fitted["boundaries"]),
                        np.asarray(self.fitted["predictions"]))
        return Column(RealNN, out.astype(np.float32))


class IsotonicRegressionCalibrator(Estimator):
    """Calibrate scores monotonically against the label: inputs
    (label: RealNN, score: RealNN) → calibrated RealNN
    (≙ IsotonicRegressionCalibrator.scala:1 wrapping ml.IsotonicRegression)."""

    in_kinds = (RealNN, RealNN)
    out_kind = RealNN
    allow_label_as_input = True

    def __init__(self, isotonic: bool = True, **params):
        super().__init__(isotonic=isotonic, **params)

    def output_name(self) -> str:
        return f"{self.input_features[1].name}_calibrated_{self.uid[-6:]}"

    def fit(self, batch: ColumnBatch) -> IsotonicRegressionCalibratorModel:
        label_f, score_f = self.input_features
        y = np.asarray(batch[label_f.name].values, np.float64)
        x = np.asarray(batch[score_f.name].values, np.float64)
        bounds, vals = pav_fit(x, y, increasing=bool(self.get("isotonic", True)))
        model = IsotonicRegressionCalibratorModel(
            fitted={"boundaries": bounds, "predictions": vals}, **self._params)
        return self._finalize_model(model)
