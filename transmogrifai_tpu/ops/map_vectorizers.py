"""Per-type map vectorizers — the specialized family the generic MapVectorizer
does not cover (reference: core/.../stages/impl/feature/
SmartTextMapVectorizer.scala:61, TextMapPivotVectorizer.scala,
MultiPickListMapVectorizer.scala, DateMapToUnitCircleVectorizer.scala,
GeolocationMapVectorizer.scala, TextMapNullEstimator.scala,
TextMapLenEstimator.scala).

All are sequence estimators: they accept any number of map features and emit
one combined OPVector.  Fit discovers each map's key set host-side (strings
never reach the device); transform lowers to a dense [N, D] block whose width
is fixed at fit time, so the scoring path stays static-shape for XLA.
"""

from __future__ import annotations

from collections import Counter
from typing import Any, Dict, List, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from .categorical import top_values_by_count
from ..columns import Column, ColumnBatch, indicator_2d
from ..stages.base import Estimator, TransformerModel
from ..types import OPVector
from ..vector_meta import (NULL_INDICATOR, OTHER_INDICATOR, VectorColumnMeta,
                           VectorMeta)
from .dates import _period_fraction
from .text import TextStats, hash_tokens_to_counts, tokenize_text


def _map_values(col) -> List[Dict[str, Any]]:
    return [v if isinstance(v, dict) else {} for v in col.values]


def _discover_keys(maps: List[Dict[str, Any]], max_keys: int,
                   allow_list=None, block_list=None) -> List[str]:
    counts: Counter = Counter()
    for m in maps:
        counts.update(m.keys())
    block = set(block_list or ())
    return sorted(k for k, _ in counts.most_common(max_keys)
                  if (allow_list is None or k in allow_list) and k not in block)


class TextMapStats:
    """Per-key TextStats monoid (≙ SmartTextMapVectorizer.TextMapStats)."""

    def __init__(self, key_stats: Optional[Dict[str, TextStats]] = None):
        self.key_stats: Dict[str, TextStats] = key_stats or {}

    def combine(self, other: "TextMapStats") -> "TextMapStats":
        out = dict(self.key_stats)
        for k, s in other.key_stats.items():
            out[k] = out[k].combine(s) if k in out else s
        return TextMapStats(out)

    @staticmethod
    def of_maps(maps: List[Dict[str, Any]], max_card: int) -> "TextMapStats":
        ks: Dict[str, TextStats] = {}
        for m in maps:
            for k, v in m.items():
                st = ks.setdefault(k, TextStats())
                if v is None:
                    continue
                s = str(v)
                if len(st.value_counts) <= max_card:
                    st.value_counts[s] += 1
                st.length_counts[len(s)] += 1
        return TextMapStats(ks)


# ---------------------------------------------------------------------------
# SmartTextMapVectorizer
# ---------------------------------------------------------------------------

class SmartTextMapVectorizerModel(TransformerModel):
    out_kind = OPVector
    is_device_op = False

    def transform(self, batch: ColumnBatch) -> Column:
        n = len(batch)
        num_hashes = self.get("num_hashes")
        track_nulls = self.get("track_nulls", True)
        blocks: List[np.ndarray] = []
        for f in self.input_features:
            maps = _map_values(batch[f.name])
            per_key = self.fitted["per_feature"][f.name]
            for k in per_key["keys"]:
                strat = per_key["strategies"][k]
                if strat == "pivot":
                    vocab = per_key["vocabs"][k]
                    width = len(vocab) + 2  # OTHER + null
                    col = np.zeros((n, width), np.float32)
                    for i, m in enumerate(maps):
                        v = m.get(k)
                        if v is None:
                            col[i, width - 1] = 1.0
                        else:
                            col[i, vocab.get(str(v), len(vocab))] = 1.0
                    blocks.append(col)
                elif strat == "ignore":
                    if track_nulls:
                        blocks.append(indicator_2d(
                            m.get(k) is None for m in maps))
                else:  # hash
                    token_lists = [tokenize_text(None if m.get(k) is None
                                                 else str(m.get(k)))
                                   for m in maps]
                    h = hash_tokens_to_counts(token_lists, num_hashes)
                    if track_nulls:
                        nulls = indicator_2d(m.get(k) is None for m in maps)
                        h = np.concatenate([h, nulls], axis=1)
                    blocks.append(h)
        arr = (np.concatenate(blocks, axis=1) if blocks
               else np.zeros((n, 0), np.float32))
        return Column(OPVector, jnp.asarray(arr), meta=self.fitted["meta"])


class SmartTextMapVectorizer(Estimator):
    """Cardinality-adaptive per-key text-map vectorization
    (≙ SmartTextMapVectorizer.scala:61): per map key, a TextStats pass decides
    pivot one-hot (≤ max_cardinality uniques), ignore (≤1 unique), or
    tokenize+hash."""

    out_kind = OPVector

    def __init__(self, max_cardinality: int = 30, top_k: int = 20,
                 min_support: int = 10, num_hashes: int = 512,
                 track_nulls: bool = True, max_keys: int = 100, **params):
        super().__init__(max_cardinality=max_cardinality, top_k=top_k,
                         min_support=min_support, num_hashes=num_hashes,
                         track_nulls=track_nulls, max_keys=max_keys, **params)

    def fit(self, batch: ColumnBatch) -> TransformerModel:
        max_card = self.get("max_cardinality")
        cols_meta: List[VectorColumnMeta] = []
        per_feature: Dict[str, Dict[str, Any]] = {}
        for f in self.input_features:
            maps = _map_values(batch[f.name])
            keys = _discover_keys(maps, self.get("max_keys", 100))
            stats = TextMapStats.of_maps(maps, max_card)
            strategies: Dict[str, str] = {}
            vocabs: Dict[str, Dict[str, int]] = {}
            kindname = f.kind.__name__
            for k in keys:
                st = stats.key_stats.get(k, TextStats())
                if st.cardinality <= max_card:
                    # the reference pivots even single-value keys
                    # (SmartTextVectorizer.scala:92-96)
                    strategies[k] = "pivot"
                    top = top_values_by_count(st.value_counts,
                                              self.get("top_k"),
                                              self.get("min_support"))
                    vocab = {v: i for i, v in enumerate(top)}
                    vocabs[k] = vocab
                    for v in top:
                        cols_meta.append(VectorColumnMeta(
                            f.name, kindname, grouping=k, indicator_value=v))
                    cols_meta.append(VectorColumnMeta(
                        f.name, kindname, grouping=k,
                        indicator_value=OTHER_INDICATOR))
                    cols_meta.append(VectorColumnMeta(
                        f.name, kindname, grouping=k,
                        indicator_value=NULL_INDICATOR))
                elif st.length_std_dev < self.get("min_length_std_dev", 0.0):
                    # ID-like key: high cardinality, near-constant value
                    # length (off by default, like the scalar SmartText)
                    strategies[k] = "ignore"
                    if self.get("track_nulls", True):
                        cols_meta.append(VectorColumnMeta(
                            f.name, kindname, grouping=k,
                            indicator_value=NULL_INDICATOR))
                else:
                    strategies[k] = "hash"
                    for j in range(self.get("num_hashes")):
                        cols_meta.append(VectorColumnMeta(
                            f.name, kindname, grouping=k,
                            descriptor_value=f"hash_{j}"))
                    if self.get("track_nulls", True):
                        cols_meta.append(VectorColumnMeta(
                            f.name, kindname, grouping=k,
                            indicator_value=NULL_INDICATOR))
            per_feature[f.name] = {"keys": keys, "strategies": strategies,
                                   "vocabs": vocabs}
        meta = VectorMeta(self.output_name(), cols_meta)
        model = SmartTextMapVectorizerModel(
            fitted={"per_feature": per_feature, "meta": meta}, **self.params)
        model.metadata["strategies"] = {
            f: dict(d["strategies"]) for f, d in per_feature.items()}
        return self._finalize_model(model)


# ---------------------------------------------------------------------------
# TextMapPivotVectorizer
# ---------------------------------------------------------------------------

class TextMapPivotVectorizerModel(TransformerModel):
    out_kind = OPVector
    is_device_op = False

    def transform(self, batch: ColumnBatch) -> Column:
        n = len(batch)
        blocks: List[np.ndarray] = []
        for f in self.input_features:
            maps = _map_values(batch[f.name])
            per_key = self.fitted["per_feature"][f.name]
            for k in per_key["keys"]:
                vocab = per_key["vocabs"][k]
                width = len(vocab) + 2
                col = np.zeros((n, width), np.float32)
                for i, m in enumerate(maps):
                    v = m.get(k)
                    if v is None:
                        col[i, width - 1] = 1.0
                    else:
                        col[i, vocab.get(str(v), len(vocab))] = 1.0
                blocks.append(col)
        arr = (np.concatenate(blocks, axis=1) if blocks
               else np.zeros((n, 0), np.float32))
        return Column(OPVector, jnp.asarray(arr), meta=self.fitted["meta"])


class TextMapPivotVectorizer(Estimator):
    """Always-pivot per-key text-map vectorizer (≙ TextMapPivotVectorizer.scala):
    every key gets top-K one-hot + OTHER + null, no hashing fallback."""

    out_kind = OPVector

    def __init__(self, top_k: int = 20, min_support: int = 10,
                 track_nulls: bool = True, max_keys: int = 100, **params):
        super().__init__(top_k=top_k, min_support=min_support,
                         track_nulls=track_nulls, max_keys=max_keys, **params)

    def fit(self, batch: ColumnBatch) -> TransformerModel:
        cols_meta: List[VectorColumnMeta] = []
        per_feature: Dict[str, Dict[str, Any]] = {}
        for f in self.input_features:
            maps = _map_values(batch[f.name])
            keys = _discover_keys(maps, self.get("max_keys", 100))
            vocabs: Dict[str, Dict[str, int]] = {}
            kindname = f.kind.__name__
            for k in keys:
                cnt = Counter(str(m[k]) for m in maps if m.get(k) is not None)
                top = top_values_by_count(cnt, self.get("top_k"),
                                          self.get("min_support"))
                vocab = {v: i for i, v in enumerate(top)}
                vocabs[k] = vocab
                for v in top:
                    cols_meta.append(VectorColumnMeta(
                        f.name, kindname, grouping=k, indicator_value=v))
                cols_meta.append(VectorColumnMeta(
                    f.name, kindname, grouping=k,
                    indicator_value=OTHER_INDICATOR))
                cols_meta.append(VectorColumnMeta(
                    f.name, kindname, grouping=k,
                    indicator_value=NULL_INDICATOR))
            per_feature[f.name] = {"keys": keys, "vocabs": vocabs}
        meta = VectorMeta(self.output_name(), cols_meta)
        return self._finalize_model(TextMapPivotVectorizerModel(
            fitted={"per_feature": per_feature, "meta": meta}, **self.params))


# ---------------------------------------------------------------------------
# MultiPickListMapVectorizer
# ---------------------------------------------------------------------------

class MultiPickListMapVectorizerModel(TransformerModel):
    out_kind = OPVector
    is_device_op = False

    def transform(self, batch: ColumnBatch) -> Column:
        n = len(batch)
        blocks: List[np.ndarray] = []
        for f in self.input_features:
            maps = _map_values(batch[f.name])
            per_key = self.fitted["per_feature"][f.name]
            for k in per_key["keys"]:
                vocab = per_key["vocabs"][k]
                width = len(vocab) + 2  # OTHER + null
                col = np.zeros((n, width), np.float32)
                for i, m in enumerate(maps):
                    s = m.get(k)
                    if not s:
                        col[i, width - 1] = 1.0
                        continue
                    for v in s:
                        j = vocab.get(str(v))
                        if j is not None:
                            col[i, j] = 1.0
                        else:
                            col[i, len(vocab)] = 1.0
                blocks.append(col)
        arr = (np.concatenate(blocks, axis=1) if blocks
               else np.zeros((n, 0), np.float32))
        return Column(OPVector, jnp.asarray(arr), meta=self.fitted["meta"])


class MultiPickListMapVectorizer(Estimator):
    """Per-key multi-hot over set values (≙ MultiPickListMapVectorizer.scala)."""

    out_kind = OPVector

    def __init__(self, top_k: int = 20, min_support: int = 10,
                 track_nulls: bool = True, max_keys: int = 100, **params):
        super().__init__(top_k=top_k, min_support=min_support,
                         track_nulls=track_nulls, max_keys=max_keys, **params)

    def fit(self, batch: ColumnBatch) -> TransformerModel:
        cols_meta: List[VectorColumnMeta] = []
        per_feature: Dict[str, Dict[str, Any]] = {}
        for f in self.input_features:
            maps = _map_values(batch[f.name])
            keys = _discover_keys(maps, self.get("max_keys", 100))
            vocabs: Dict[str, Dict[str, int]] = {}
            kindname = f.kind.__name__
            for k in keys:
                cnt: Counter = Counter()
                for m in maps:
                    for v in (m.get(k) or ()):
                        cnt[str(v)] += 1
                top = top_values_by_count(cnt, self.get("top_k"),
                                          self.get("min_support"))
                vocab = {v: i for i, v in enumerate(top)}
                vocabs[k] = vocab
                for v in top:
                    cols_meta.append(VectorColumnMeta(
                        f.name, kindname, grouping=k, indicator_value=v))
                cols_meta.append(VectorColumnMeta(
                    f.name, kindname, grouping=k,
                    indicator_value=OTHER_INDICATOR))
                cols_meta.append(VectorColumnMeta(
                    f.name, kindname, grouping=k,
                    indicator_value=NULL_INDICATOR))
            per_feature[f.name] = {"keys": keys, "vocabs": vocabs}
        meta = VectorMeta(self.output_name(), cols_meta)
        return self._finalize_model(MultiPickListMapVectorizerModel(
            fitted={"per_feature": per_feature, "meta": meta}, **self.params))


# ---------------------------------------------------------------------------
# DateMapToUnitCircleVectorizer
# ---------------------------------------------------------------------------

class DateMapToUnitCircleVectorizerModel(TransformerModel):
    out_kind = OPVector
    is_device_op = False

    def transform(self, batch: ColumnBatch) -> Column:
        n = len(batch)
        period = self.get("time_period", "HourOfDay")
        blocks: List[np.ndarray] = []
        for f in self.input_features:
            maps = _map_values(batch[f.name])
            for k in self.fitted["per_feature"][f.name]:
                vals = np.array([float(m.get(k) or 0) for m in maps])
                present = np.array([m.get(k) is not None for m in maps])
                frac = np.asarray(_period_fraction(vals, period))
                ang = 2 * np.pi * frac
                blocks.append(np.stack(
                    [np.where(present, np.sin(ang), 0.0),
                     np.where(present, np.cos(ang), 0.0)],
                    axis=1).astype(np.float32))
        arr = (np.concatenate(blocks, axis=1) if blocks
               else np.zeros((n, 0), np.float32))
        return Column(OPVector, jnp.asarray(arr), meta=self.fitted["meta"])


class DateMapToUnitCircleVectorizer(Estimator):
    """Per-key date → (sin, cos) unit-circle encoding
    (≙ DateMapToUnitCircleVectorizer.scala; default period HourOfDay)."""

    out_kind = OPVector

    def __init__(self, time_period: str = "HourOfDay", max_keys: int = 100,
                 **params):
        super().__init__(time_period=time_period, max_keys=max_keys, **params)

    def fit(self, batch: ColumnBatch) -> TransformerModel:
        cols_meta: List[VectorColumnMeta] = []
        per_feature: Dict[str, List[str]] = {}
        period = self.get("time_period", "HourOfDay")
        for f in self.input_features:
            maps = _map_values(batch[f.name])
            keys = _discover_keys(maps, self.get("max_keys", 100))
            per_feature[f.name] = keys
            for k in keys:
                for fn_name in ("sin", "cos"):
                    cols_meta.append(VectorColumnMeta(
                        f.name, f.kind.__name__, grouping=k,
                        descriptor_value=f"{fn_name}({period})"))
        meta = VectorMeta(self.output_name(), cols_meta)
        return self._finalize_model(DateMapToUnitCircleVectorizerModel(
            fitted={"per_feature": per_feature, "meta": meta}, **self.params))


# ---------------------------------------------------------------------------
# GeolocationMapVectorizer
# ---------------------------------------------------------------------------

class GeolocationMapVectorizerModel(TransformerModel):
    out_kind = OPVector
    is_device_op = False

    def transform(self, batch: ColumnBatch) -> Column:
        n = len(batch)
        track_nulls = self.get("track_nulls", True)
        blocks: List[np.ndarray] = []
        for f in self.input_features:
            maps = _map_values(batch[f.name])
            per_key = self.fitted["per_feature"][f.name]
            for k in per_key["keys"]:
                fill = np.asarray(per_key["fills"][k], np.float32)
                col = np.zeros((n, 4 if track_nulls else 3), np.float32)
                for i, m in enumerate(maps):
                    v = m.get(k)
                    if v:
                        col[i, :3] = np.asarray(list(v)[:3], np.float32)
                    else:
                        col[i, :3] = fill
                        if track_nulls:
                            col[i, 3] = 1.0
                blocks.append(col)
        arr = (np.concatenate(blocks, axis=1) if blocks
               else np.zeros((n, 0), np.float32))
        return Column(OPVector, jnp.asarray(arr), meta=self.fitted["meta"])


class GeolocationMapVectorizer(Estimator):
    """Per-key (lat, lon, accuracy) with mean fill + null indicator
    (≙ GeolocationMapVectorizer.scala)."""

    out_kind = OPVector

    def __init__(self, track_nulls: bool = True, max_keys: int = 100,
                 default_location: Optional[Sequence[float]] = None, **params):
        super().__init__(track_nulls=track_nulls, max_keys=max_keys,
                         default_location=default_location, **params)

    def fit(self, batch: ColumnBatch) -> TransformerModel:
        cols_meta: List[VectorColumnMeta] = []
        per_feature: Dict[str, Dict[str, Any]] = {}
        default = self.get("default_location")
        for f in self.input_features:
            maps = _map_values(batch[f.name])
            keys = _discover_keys(maps, self.get("max_keys", 100))
            fills: Dict[str, np.ndarray] = {}
            kindname = f.kind.__name__
            for k in keys:
                vals = [list(m[k])[:3] for m in maps if m.get(k)]
                # plain float lists: fitted nested dicts must stay JSON-safe
                if default is not None:
                    fills[k] = [float(x) for x in list(default)[:3]]
                else:
                    fills[k] = ([float(x) for x in
                                 np.mean(np.asarray(vals, np.float32), axis=0)]
                                if vals else [0.0, 0.0, 0.0])
                for d in ("lat", "lon", "accuracy"):
                    cols_meta.append(VectorColumnMeta(
                        f.name, kindname, grouping=k, descriptor_value=d))
                if self.get("track_nulls", True):
                    cols_meta.append(VectorColumnMeta(
                        f.name, kindname, grouping=k,
                        indicator_value=NULL_INDICATOR))
            per_feature[f.name] = {"keys": keys, "fills": fills}
        meta = VectorMeta(self.output_name(), cols_meta)
        return self._finalize_model(GeolocationMapVectorizerModel(
            fitted={"per_feature": per_feature, "meta": meta}, **self.params))


# ---------------------------------------------------------------------------
# TextMapNullEstimator / TextMapLenEstimator
# ---------------------------------------------------------------------------

class TextMapNullModel(TransformerModel):
    out_kind = OPVector
    is_device_op = False

    def transform(self, batch: ColumnBatch) -> Column:
        n = len(batch)
        blocks: List[np.ndarray] = []
        for f in self.input_features:
            maps = _map_values(batch[f.name])
            for k in self.fitted["per_feature"][f.name]:
                blocks.append(indicator_2d(m.get(k) is None for m in maps))
        arr = (np.concatenate(blocks, axis=1) if blocks
               else np.zeros((n, 0), np.float32))
        return Column(OPVector, jnp.asarray(arr), meta=self.fitted["meta"])


class TextMapNullEstimator(Estimator):
    """Per-key null indicators only (≙ TextMapNullEstimator.scala)."""

    out_kind = OPVector

    def __init__(self, max_keys: int = 100, **params):
        super().__init__(max_keys=max_keys, **params)

    def fit(self, batch: ColumnBatch) -> TransformerModel:
        cols_meta: List[VectorColumnMeta] = []
        per_feature: Dict[str, List[str]] = {}
        for f in self.input_features:
            maps = _map_values(batch[f.name])
            keys = _discover_keys(maps, self.get("max_keys", 100))
            per_feature[f.name] = keys
            for k in keys:
                cols_meta.append(VectorColumnMeta(
                    f.name, f.kind.__name__, grouping=k,
                    indicator_value=NULL_INDICATOR))
        meta = VectorMeta(self.output_name(), cols_meta)
        return self._finalize_model(TextMapNullModel(
            fitted={"per_feature": per_feature, "meta": meta}, **self.params))


class TextMapLenModel(TransformerModel):
    out_kind = OPVector
    is_device_op = False

    def transform(self, batch: ColumnBatch) -> Column:
        n = len(batch)
        blocks: List[np.ndarray] = []
        for f in self.input_features:
            maps = _map_values(batch[f.name])
            for k in self.fitted["per_feature"][f.name]:
                lens = np.fromiter(
                    (0.0 if m.get(k) is None else float(len(str(m[k])))
                     for m in maps), np.float32)
                blocks.append(lens.reshape(-1, 1))
        arr = (np.concatenate(blocks, axis=1) if blocks
               else np.zeros((n, 0), np.float32))
        return Column(OPVector, jnp.asarray(arr), meta=self.fitted["meta"])


class TextMapLenEstimator(Estimator):
    """Per-key text value lengths (≙ TextMapLenEstimator.scala)."""

    out_kind = OPVector

    def __init__(self, max_keys: int = 100, **params):
        super().__init__(max_keys=max_keys, **params)

    def fit(self, batch: ColumnBatch) -> TransformerModel:
        cols_meta: List[VectorColumnMeta] = []
        per_feature: Dict[str, List[str]] = {}
        for f in self.input_features:
            maps = _map_values(batch[f.name])
            keys = _discover_keys(maps, self.get("max_keys", 100))
            per_feature[f.name] = keys
            for k in keys:
                cols_meta.append(VectorColumnMeta(
                    f.name, f.kind.__name__, grouping=k,
                    descriptor_value="textLen"))
        meta = VectorMeta(self.output_name(), cols_meta)
        return self._finalize_model(TextMapLenModel(
            fitted={"per_feature": per_feature, "meta": meta}, **self.params))
