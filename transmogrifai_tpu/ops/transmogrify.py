"""Transmogrifier — automatic per-type feature vectorization (reference:
core/.../stages/impl/feature/Transmogrifier.scala:92, the type-dispatch match
at :116-345, defaults at TransmogrifierDefaults:52-88, and the DSL
``.transmogrify()`` at dsl/RichFeaturesCollection.scala:69).

Groups input features by kind, applies the default vectorizer per group, and
combines all blocks with VectorsCombiner into one feature vector.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Type

from ..features import Feature
from ..types import (Base64, Binary, City, ComboBox, Country, Currency, Date,
                     DateList, DateTime, DateTimeList, Email, FeatureType,
                     Geolocation, ID, Integral, MultiPickList, OPMap, OPVector,
                     Percent, Phone, PickList, PostalCode, Real, RealNN, State,
                     Street, Text, TextArea, TextList, URL, is_map_kind)


class TransmogrifierDefaults:
    """≙ TransmogrifierDefaults (Transmogrifier.scala:52-88)."""

    DEFAULT_NUM_OF_FEATURES = 512
    MAX_NUM_OF_FEATURES = 16384
    TOP_K = 20
    MIN_SUPPORT = 10
    MAX_CATEGORICAL_CARDINALITY = 30
    FILL_VALUE = 0.0
    BINARY_FILL_VALUE = False
    TRACK_NULLS = True
    TRACK_INVALID = False
    TRACK_TEXT_LEN = False
    MIN_DOC_FREQUENCY = 0
    CIRCULAR_DATE_REPRESENTATIONS = ("HourOfDay", "DayOfWeek", "DayOfMonth", "DayOfYear")
    REFERENCE_DATE_MS = 1500000000000  # fixed anchor like joda's default


def _group_key(kind: Type[FeatureType]) -> str:
    if issubclass(kind, RealNN):
        return "realnn"
    if issubclass(kind, Binary):
        return "binary"
    if issubclass(kind, (Date, DateTime)):
        return "date"
    if issubclass(kind, Integral):
        return "integral"
    if issubclass(kind, (Real, Percent, Currency)):
        return "real"
    if issubclass(kind, (PickList, ComboBox, ID, Country, State, City,
                         PostalCode, Street)):
        return "categorical"
    if issubclass(kind, Email):
        return "email"
    if issubclass(kind, URL):
        return "url"
    if issubclass(kind, Phone):
        return "phone"
    if issubclass(kind, Base64):
        return "base64"
    if issubclass(kind, (TextArea, Text)):
        return "text"
    if issubclass(kind, TextList):
        return "textlist"
    if issubclass(kind, (DateList, DateTimeList)):
        return "datelist"
    if issubclass(kind, MultiPickList):
        return "multipicklist"
    if issubclass(kind, Geolocation):
        return "geolocation"
    if issubclass(kind, OPVector):
        return "vector"
    if is_map_kind(kind):
        return "map"
    raise TypeError(f"transmogrify: unsupported feature kind {kind.__name__}")


def transmogrify(features: Sequence[Feature],
                 top_k: int = TransmogrifierDefaults.TOP_K,
                 min_support: int = TransmogrifierDefaults.MIN_SUPPORT,
                 num_hashes: int = TransmogrifierDefaults.DEFAULT_NUM_OF_FEATURES,
                 max_categorical_cardinality: int = TransmogrifierDefaults.MAX_CATEGORICAL_CARDINALITY,
                 track_nulls: bool = TransmogrifierDefaults.TRACK_NULLS,
                 label: Optional[Feature] = None) -> Feature:
    """Auto-vectorize a heterogeneous feature list into one OPVector feature."""
    from .categorical import OneHotEstimator
    from .combiner import VectorsCombiner
    from .numeric import (BinaryVectorizer, IntegralVectorizer,
                          RealNNVectorizer, RealVectorizer)

    groups: Dict[str, List[Feature]] = {}
    for f in features:
        groups.setdefault(_group_key(f.kind), []).append(f)

    # specialized text kinds route through their validators/extractors first
    # (≙ TextTransmogrify cases, Transmogrifier.scala:116-180: email/url →
    # domain picklist, base64 → mime-type picklist, phone → isValid binary)
    from .text_specialized import (EmailToPickListTransformer,
                                   IsValidPhoneDefaultCountry,
                                   MimeTypeDetector, UrlToPickListTransformer)
    specialized_routes = [
        ("email", EmailToPickListTransformer, "categorical"),
        ("url", UrlToPickListTransformer, "categorical"),
        ("base64", MimeTypeDetector, "categorical"),
        ("phone", IsValidPhoneDefaultCountry, "binary"),
    ]
    for group, stage_cls, dest in specialized_routes:
        for f in groups.pop(group, []):
            st = stage_cls()
            st.set_input(f)
            groups.setdefault(dest, []).append(st.get_output())

    blocks: List[Feature] = []
    for key in sorted(groups):
        feats = groups[key]
        if key == "real":
            st = RealVectorizer(fill_mode="mean", track_nulls=track_nulls)
        elif key == "realnn":
            st = RealNNVectorizer()
        elif key == "integral":
            st = IntegralVectorizer(fill_mode="mode", track_nulls=track_nulls)
        elif key == "binary":
            st = BinaryVectorizer(track_nulls=track_nulls)
        elif key == "categorical":
            st = OneHotEstimator(top_k=top_k, min_support=min_support,
                                 track_nulls=track_nulls)
        elif key == "text":
            from .text import SmartTextVectorizer
            st = SmartTextVectorizer(
                max_cardinality=max_categorical_cardinality, top_k=top_k,
                min_support=min_support, num_hashes=num_hashes,
                track_nulls=track_nulls)
        elif key == "date":
            from .dates import DateToUnitCircleVectorizer
            st = DateToUnitCircleVectorizer(track_nulls=track_nulls)
        elif key == "datelist":
            from .dates import DateListVectorizer
            st = DateListVectorizer(track_nulls=track_nulls)
        elif key == "multipicklist":
            from .collections import MultiPickListVectorizer
            st = MultiPickListVectorizer(top_k=top_k, min_support=min_support,
                                         track_nulls=track_nulls)
        elif key == "textlist":
            from .text import TextListVectorizer
            st = TextListVectorizer(num_hashes=num_hashes)
        elif key == "geolocation":
            from .geo import GeolocationVectorizer
            st = GeolocationVectorizer(track_nulls=track_nulls)
        elif key == "map":
            # per-value-kind dispatch, mirroring the reference's per-map-type
            # cases (Transmogrifier.scala:142-217)
            from .map_vectorizers import (GeolocationMapVectorizer,
                                          MultiPickListMapVectorizer,
                                          SmartTextMapVectorizer,
                                          TextMapPivotVectorizer)
            from .maps import MapVectorizer
            from ..types import map_value_kind
            smart_text, pivot_text, multi, geo, generic = [], [], [], [], []
            for f in feats:
                vk = map_value_kind(f.kind)
                if issubclass(vk, (TextArea, Text)) and vk not in (
                        PickList, ComboBox, ID, Country, State, City,
                        PostalCode, Street, Email, URL, Phone, Base64):
                    smart_text.append(f)
                elif issubclass(vk, (PickList, ComboBox, ID, Country, State,
                                     City, PostalCode, Street, Email, URL,
                                     Phone, Base64)):
                    pivot_text.append(f)
                elif issubclass(vk, MultiPickList):
                    multi.append(f)
                elif issubclass(vk, Geolocation):
                    geo.append(f)
                else:
                    generic.append(f)
            if smart_text:
                st = SmartTextMapVectorizer(
                    max_cardinality=max_categorical_cardinality, top_k=top_k,
                    min_support=min_support, num_hashes=num_hashes,
                    track_nulls=track_nulls)
                st.set_input(*smart_text)
                blocks.append(st.get_output())
            if pivot_text:
                st = TextMapPivotVectorizer(top_k=top_k, min_support=min_support,
                                            track_nulls=track_nulls)
                st.set_input(*pivot_text)
                blocks.append(st.get_output())
            if multi:
                st = MultiPickListMapVectorizer(
                    top_k=top_k, min_support=min_support, track_nulls=track_nulls)
                st.set_input(*multi)
                blocks.append(st.get_output())
            if geo:
                st = GeolocationMapVectorizer(track_nulls=track_nulls)
                st.set_input(*geo)
                blocks.append(st.get_output())
            for f in generic:
                st = MapVectorizer(top_k=top_k, min_support=min_support,
                                   track_nulls=track_nulls)
                st.set_input(f)
                blocks.append(st.get_output())
            continue
        elif key == "vector":
            blocks.extend(feats)
            continue
        else:
            raise TypeError(f"transmogrify: no vectorizer for group {key}")
        st.set_input(*feats)
        blocks.append(st.get_output())

    combiner = VectorsCombiner()
    combiner.set_input(*blocks)
    return combiner.get_output()
