"""One-pass text column profile shared by every host consumer of a text
column (reference parity targets: RawFeatureFilter's presence + hashed value
distribution RawFeatureFilter.scala:137, SmartTextVectorizer's TextStats fit
pass SmartTextVectorizer.scala:80-123, OpHashingTF's tokenize+hash transform).

The transmogrification hot path used to rescan each text column once per
consumer — a Python-object walk over millions of cells each time.  Here ONE
native pass (native/textprof.cpp) computes *parameter-free* per-row
products, cached on the Column instance:

* ``null``/``empty``/``lengths``  — presence + TextStats length stats
* ``crc``      — full zlib crc32 per value; rebin with ``% text_bins`` for
  any RawFeatureFilter configuration
* ``tok_lens``/``tok_hash`` — tokens per row + full 32-bit FNV-1a per
  token; rebucket with ``% num_hashes`` for any hash width

Value interning (``values(cap)``) is the only cap-dependent product and is
cached per cap.  All consumers fall back to pure Python when the native
toolchain is absent — identical results, slower.
"""

from __future__ import annotations

import zlib
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np


@dataclass
class InternedValues:
    """First-occurrence-ordered distinct values with counts and row codes.

    ``codes``: -1 null, -2 seen only after the freeze cap, else index into
    ``uniq``.  ``frozen`` is True when the TextStats freeze engaged (counts
    stopped accumulating; ``uniq`` holds cap+1 values).
    """

    uniq: List[str]
    counts: np.ndarray       # int64[U]
    codes: np.ndarray        # int32[N]
    cap: int
    frozen: bool

    def value_counts(self) -> Dict[str, int]:
        return {v: int(c) for v, c in zip(self.uniq, self.counts)}


@dataclass
class TextProfile:
    null: np.ndarray         # bool[N]
    empty: np.ndarray        # bool[N]
    lengths: np.ndarray      # int32[N] (code points; 0 for null)
    crc: np.ndarray          # uint32[N] (0 for null)
    tok_lens: np.ndarray     # int32[N]
    tok_hash: np.ndarray     # uint32[total] full FNV-1a per token
    _interned: Dict[int, InternedValues] = field(default_factory=dict)
    _strings: Optional[np.ndarray] = None   # kept for lazy interning
    _device_packed: Dict[int, object] = field(default_factory=dict)

    @property
    def presence(self) -> np.ndarray:
        """Present = non-null and non-empty (filters._value_presence)."""
        return ~(self.null | self.empty)

    def crc_hist(self, text_bins: int) -> np.ndarray:
        """Hashed whole-value distribution over present rows — exactly
        filters._histogram_of's text branch (crc32 % text_bins)."""
        bins = (self.crc[self.presence] % np.uint32(text_bins)).astype(
            np.int64)
        return np.bincount(bins, minlength=text_bins).astype(np.float64)

    def length_counts(self) -> Dict[int, int]:
        """≙ TextStats.length_counts (lengths of all non-null values)."""
        ls = self.lengths[~self.null]
        if not ls.size:
            return {}
        uniq, cnt = np.unique(ls, return_counts=True)
        return {int(l): int(c) for l, c in zip(uniq, cnt)}

    def buckets(self, num_hashes: int) -> Tuple[np.ndarray, np.ndarray]:
        """(lens int32[N], flat bucket ids int32[total]) for the hashing
        trick at any ``num_hashes`` — one modulo over the cached full
        hashes instead of a re-tokenize."""
        return (self.tok_lens,
                (self.tok_hash % np.uint32(num_hashes)).astype(np.int32))

    def device_ids(self, num_hashes: int):
        """Packed token-bucket ids resident on device (3 × 10-bit ids per
        int32 word; ops/text.py pack/scatter pair), cached per hash width.
        ``prefetch`` starts the async host→device transfer early so the
        slow link overlaps RFF/fit host work instead of serializing after
        it.  None when the width needs the unpacked path."""
        if num_hashes >= 1024:
            return None
        dev = self._device_packed.get(num_hashes)
        if dev is None:
            import jax

            from .text import _pack_ids3, _sentinel3, _size_class
            _, flat = self.buckets(num_hashes)
            words = _pack_ids3(flat, num_hashes)
            cap = _size_class(words.size)
            wp = np.full(cap, _sentinel3(num_hashes), np.int32)
            wp[:words.size] = words
            dev = jax.device_put(wp)      # async; consumers queue on it
            from ..profiling import add_host_link_bytes
            add_host_link_bytes(wp.nbytes)
            self._device_packed[num_hashes] = dev
        return dev

    def prefetch(self, num_hashes: int) -> None:
        try:
            self.device_ids(num_hashes)
        except Exception:  # pragma: no cover — prefetch is best-effort
            pass

    def values(self, cap: int = -1) -> InternedValues:
        """Interned distinct values; ``cap`` >= 0 applies the TextStats
        freeze semantics (ops/text.py TextStats.of_column), cap < 0 counts
        exactly (OneHotEstimator's Counter).

        A cached interning is reused across cap requests whenever the
        results are provably identical: a non-frozen capped run equals the
        exact run, and an exact run with U distinct values equals any
        capped run with cap >= U (the freeze never engages)."""
        if cap in self._interned:
            return self._interned[cap]
        for iv in self._interned.values():
            if not iv.frozen and (cap < 0 or len(iv.uniq) <= cap):
                return iv
        self._interned[cap] = _intern(self._strings, cap)
        return self._interned[cap]


def _py_scan(strings: Sequence, min_token_len: int = 1) -> TextProfile:
    """Pure-Python scan — same products as native/textprof.cpp scan()."""
    from .text import fnv1a_32, tokenize_text

    n = len(strings)
    null = np.zeros(n, bool)
    empty = np.zeros(n, bool)
    lengths = np.zeros(n, np.int32)
    crc = np.zeros(n, np.uint32)
    tok_lens = np.zeros(n, np.int32)
    hashes: List[int] = []
    for i, s in enumerate(strings):
        if s is None:
            null[i] = True
            continue
        lengths[i] = len(s)
        b = s.encode("utf-8")
        if not b:
            empty[i] = True
        crc[i] = zlib.crc32(b)
        toks = tokenize_text(s, min_token_len)
        tok_lens[i] = len(toks)
        hashes.extend(fnv1a_32(t) for t in toks)
    return TextProfile(null, empty, lengths, crc, tok_lens,
                       np.asarray(hashes, np.uint32))


def _py_intern(strings: Sequence, cap: int) -> InternedValues:
    table: Dict[str, int] = {}
    uniq: List[str] = []
    counts: List[int] = []
    codes = np.empty(len(strings), np.int32)
    for i, s in enumerate(strings):
        if s is None:
            codes[i] = -1
            continue
        # TextStats freeze (of_column): counting — inserts and increments
        # alike — happens only while the table holds <= cap distinct values
        can_count = cap < 0 or len(uniq) <= cap
        j = table.get(s)
        if j is not None:
            codes[i] = j
            if can_count:
                counts[j] += 1
            continue
        if not can_count:
            codes[i] = -2
            continue
        j = len(uniq)
        table[s] = j
        uniq.append(s)
        counts.append(1)
        codes[i] = j
    return InternedValues(uniq, np.asarray(counts, np.int64), codes, cap,
                          frozen=cap >= 0 and len(uniq) > cap)


def _intern(strings, cap: int) -> InternedValues:
    from ..native import load

    native = load("textprof")
    if native is None:
        return _py_intern(strings, cap)
    uniq, counts, codes = native.intern(list(strings), cap)
    return InternedValues(list(uniq), counts, codes, cap,
                          frozen=cap >= 0 and len(uniq) > cap)


def scan_strings(strings, min_token_len: int = 1) -> TextProfile:
    """Profile a string sequence (native pass when available)."""
    from ..native import load
    from .text import fnv1a_32, tokenize_text

    native = load("textprof")
    if native is None:
        prof = _py_scan(strings, min_token_len)
    else:
        d = native.scan(list(strings), min_token_len)
        lens = d["tok_lens"]
        hashes = d["tok_hash"]
        fallback = d["fallback"]
        if fallback:
            # non-ASCII rows: splice the Python tokenizer's hashes in place
            # for exact unicode case-folding parity
            fb = {i: np.asarray(
                [fnv1a_32(t) for t in tokenize_text(strings[i],
                                                    min_token_len)],
                np.uint32) for i in fallback}
            out_lens = lens.copy()
            pieces: List[np.ndarray] = []
            pos = 0
            for i, L in enumerate(lens):
                if L < 0:
                    out_lens[i] = len(fb[i])
                    pieces.append(fb[i])
                elif L:
                    pieces.append(hashes[pos:pos + L])
                    pos += L
            hashes = (np.concatenate(pieces).astype(np.uint32) if pieces
                      else np.zeros(0, np.uint32))
            lens = out_lens
        prof = TextProfile(d["null"].astype(bool), d["empty"].astype(bool),
                           d["lengths"], d["crc"], lens, hashes)
    prof._strings = strings if isinstance(strings, np.ndarray) \
        else np.asarray(list(strings), dtype=object)
    return prof


def column_profile(col) -> TextProfile:
    """Profile of a text-kind Column, computed once and cached on the
    instance (Columns are immutable throughout the framework)."""
    prof = getattr(col, "_text_profile", None)
    if prof is None:
        from .categorical import _col_strings
        prof = scan_strings(_col_strings(col))
        try:
            object.__setattr__(col, "_text_profile", prof)
        except Exception:  # pragma: no cover — exotic column subtype
            pass
    return prof
