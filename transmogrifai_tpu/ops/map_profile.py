"""One-pass columnar expansion of numeric-valued map columns, cached on the
Column instance (native/mapprof.cpp; reference analogs: the per-key map
expansion in OPMapVectorizer.scala and RawFeatureFilter's PreparedFeatures).

Every host consumer of a RealMap/IntegralMap-like column — RawFeatureFilter
ranges + histograms, MapVectorizer fit fills + transform — used to walk the
million-dict object array independently.  ``map_expansion`` walks it ONCE
(native when available) into dense arrays all consumers share.

Columns containing bools or non-numeric values return ``None`` and callers
keep their exact Python paths (bool handling differs per consumer in pinned
ways; see filters.numeric_ranges vs filters._histogram_of).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional

import numpy as np


@dataclass
class MapExpansion:
    keys: List[str]           # first-occurrence order
    vals: np.ndarray          # float64[N, K], NaN where absent/None
    present: np.ndarray       # bool[N, K]  (value present and not None)
    in_dict: np.ndarray       # int64[K]    (key in dict, even if value None)
    nonempty: np.ndarray      # bool[N]     (row is a non-empty dict)

    def key_index(self) -> Dict[str, int]:
        return {k: j for j, k in enumerate(self.keys)}


def _py_expand(maps) -> Optional[MapExpansion]:
    n = len(maps)
    key_ids: Dict[str, int] = {}
    cols: List[np.ndarray] = []
    pres: List[np.ndarray] = []
    in_dict: List[int] = []
    nonempty = np.zeros(n, bool)
    for i, m in enumerate(maps):
        if m is None:
            continue
        if not isinstance(m, dict):
            return None
        if m:
            nonempty[i] = True
        for k, v in m.items():
            if not isinstance(k, str):
                return None
            j = key_ids.get(k)
            if j is None:
                j = len(cols)
                key_ids[k] = j
                cols.append(np.full(n, np.nan))
                pres.append(np.zeros(n, bool))
                in_dict.append(0)
            in_dict[j] += 1
            if v is None:
                continue
            if isinstance(v, bool) or not isinstance(
                    v, (int, float, np.integer, np.floating)):
                return None
            cols[j][i] = float(v)
            pres[j][i] = True
    K = len(cols)
    vals = (np.stack(cols, axis=1) if K else np.zeros((n, 0)))
    present = (np.stack(pres, axis=1) if K else np.zeros((n, 0), bool))
    return MapExpansion(list(key_ids), vals, present,
                        np.asarray(in_dict, np.int64), nonempty)


def expand_maps(maps) -> Optional[MapExpansion]:
    from ..native import load

    native = load("mapprof")
    if native is None:
        return _py_expand(maps)
    try:
        keys, vals, present, in_dict, nonempty = native.expand(list(maps))
    except TypeError:
        return None     # bool / non-numeric values → exact Python paths
    return MapExpansion(list(keys), vals, present.astype(bool), in_dict,
                        nonempty.astype(bool))


_MISS = object()


def map_expansion(col) -> Optional[MapExpansion]:
    """Cached columnar expansion of a map Column (None when the values are
    not purely numeric — callers fall back to their Python paths)."""
    cached = getattr(col, "_map_expansion", _MISS)
    if cached is _MISS:
        cached = expand_maps(col.values)
        try:
            object.__setattr__(col, "_map_expansion", cached)
        except Exception:  # pragma: no cover — exotic column subtype
            pass
    return cached
