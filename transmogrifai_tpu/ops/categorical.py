"""Categorical vectorizers (reference: core/.../stages/impl/feature/
OpOneHotVectorizer.scala, OpStringIndexer.scala, OpIndexToString.scala).

One-hot pivot: fit finds the top-K values per feature by count (min support),
transform maps strings → fixed vocabulary ids on host (numpy hash-map lookup),
then one-hot expansion is a pure device op.  Static shapes: the vocab is
resolved at fit time, so the transform jits (SURVEY.md §7 hard part (c)).
"""

from __future__ import annotations

from collections import Counter
from typing import Dict, List, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from ..columns import Column, ColumnBatch
from ..stages.base import Estimator, Transformer, TransformerModel
from ..types import Integral, OPVector, Real, RealNN, Text
from ..vector_meta import (NULL_INDICATOR, OTHER_INDICATOR, VectorColumnMeta,
                           VectorMeta)


def _col_strings(col: Column) -> np.ndarray:
    """Host view of a text-ish column as object array of str|None."""
    if col.is_host_object():
        return col.values
    vals = np.asarray(col.values).astype(str)
    if col.mask is not None:
        out = vals.astype(object)
        out[~np.asarray(col.mask)] = None
        return out
    return vals.astype(object)


def top_values_by_count(counts, top_k: int, min_support: int):
    """Reference top-value selection (SmartTextVectorizer.scala:97-100,
    OpOneHotVectorizer): drop values below ``min_support``, order by
    (count desc, value asc), take ``top_k``.  The returned ORDER is the
    pivot column layout — most frequent value first."""
    eligible = [(v, c) for v, c in counts.items() if c >= min_support]
    eligible.sort(key=lambda vc: (-vc[1], vc[0]))
    return [v for v, _ in eligible[:top_k]]


def encode_with_vocab(values: np.ndarray, vocab: Dict[str, int], other_id: int) -> np.ndarray:
    """strings → int ids; None→other_id+1 (null slot)."""
    null_id = other_id + 1
    out = np.full(len(values), other_id, dtype=np.int32)
    for i, v in enumerate(values):
        if v is None:
            out[i] = null_id
        else:
            out[i] = vocab.get(v, other_id)
    return out


def encode_column(col: Column, vocab: Dict[str, int], other_id: int) -> np.ndarray:
    """``encode_with_vocab`` through the cached one-pass column profile:
    the per-row dict probe collapses to one small table lookup over the
    interned codes (native/textprof.cpp)."""
    if not col.is_host_object():
        return encode_with_vocab(_col_strings(col), vocab, other_id)
    from .text_profile import column_profile
    iv = column_profile(col).values(-1)
    if not iv.uniq:    # all-null column
        return np.full(len(iv.codes), other_id + 1, np.int32)
    table = np.fromiter((vocab.get(v, other_id) for v in iv.uniq), np.int32,
                        count=len(iv.uniq))
    return np.where(iv.codes < 0, np.int32(other_id + 1),
                    table[np.maximum(iv.codes, 0)]).astype(np.int32)


class OneHotModel(TransformerModel):
    out_kind = OPVector
    is_device_op = False  # host vocab lookup, then device one-hot
    supports_staging = True

    def transform_staged(self, batch: ColumnBatch):
        """Host prologue: vocab-encode each feature through the cached
        column profile (narrow uint8 wire).  Device body: one-hot expand +
        concat — fuses into the surrounding XLA program."""
        track_other = self.get("track_other", True)
        track_nulls = self.get("track_nulls", True)
        wire = {}
        plan = []
        for i, f in enumerate(self.input_features):
            if f.name in batch and not batch[f.name].is_host_object():
                return None
            vocab: Dict[str, int] = self.fitted["vocabs"][f.name]
            other_id = len(vocab)
            ids = encode_column(batch[f.name], vocab, other_id)
            cols = list(range(other_id))
            if track_other:
                cols.append(other_id)
            if track_nulls:
                cols.append(other_id + 1)
            wire[f"ids{i}"] = (ids.astype(np.uint8) if other_id + 1 < 256
                               else ids)
            plan.append((f"ids{i}", np.asarray(cols, np.int32)))
        n = len(batch)
        meta = self.fitted["meta"]

        def body(w):
            outs = []
            for key, cols in plan:
                if len(cols):
                    ids = jnp.asarray(w[key]).astype(jnp.int32)
                    outs.append((ids[:, None] == jnp.asarray(cols)[None, :]
                                 ).astype(jnp.float32))
                else:
                    outs.append(jnp.zeros((w[key].shape[0], 0), jnp.float32))
            return Column(OPVector,
                          jnp.concatenate(outs, axis=1) if outs else
                          jnp.zeros((n, 0), jnp.float32), meta=meta)

        return wire, body

    def transform(self, batch: ColumnBatch) -> Column:
        outs = []
        track_other = self.get("track_other", True)
        track_nulls = self.get("track_nulls", True)
        for f in self.input_features:
            vocab: Dict[str, int] = self.fitted["vocabs"][f.name]
            other_id = len(vocab)
            ids = encode_column(batch[f.name], vocab, other_id)
            # full encoding always has [vocab..., OTHER, NULL]; select only the
            # slots this model tracks so columns stay aligned with the meta
            cols = list(range(other_id))
            if track_other:
                cols.append(other_id)
            if track_nulls:
                cols.append(other_id + 1)
            # ship the narrowest id dtype and expand on DEVICE — a host-built
            # [N, width] f32 block costs width×4 bytes/row over the slow link
            if cols:
                wire = (ids.astype(np.uint8) if other_id + 1 < 256 else ids)
                onehot = (jnp.asarray(wire).astype(jnp.int32)[:, None]
                          == jnp.asarray(np.asarray(cols, np.int32))[None, :]
                          ).astype(jnp.float32)
            else:
                onehot = jnp.zeros((len(ids), 0), jnp.float32)
            outs.append(onehot)
        return Column(OPVector, jnp.concatenate(outs, axis=1) if outs else
                      jnp.zeros((len(batch), 0)), meta=self.fitted["meta"])


class OneHotEstimator(Estimator):
    """Pivot top-K categorical values into indicator columns with OTHER and
    null slots (≙ OpOneHotVectorizer/OneHotEstimator)."""

    out_kind = OPVector

    def __init__(self, top_k: int = 20, min_support: int = 10,
                 track_nulls: bool = True, track_other: bool = True,
                 max_pct_cardinality: float = 1.0, **params):
        super().__init__(top_k=top_k, min_support=min_support,
                         track_nulls=track_nulls, track_other=track_other,
                         max_pct_cardinality=max_pct_cardinality, **params)

    def fit(self, batch: ColumnBatch) -> TransformerModel:
        vocabs: Dict[str, Dict[str, int]] = {}
        cols_meta: List[VectorColumnMeta] = []
        top_k, min_support = self.get("top_k"), self.get("min_support")
        for f in self.input_features:
            col = batch[f.name]
            if col.is_host_object():
                from .text_profile import column_profile
                counts = column_profile(col).values(-1).value_counts()
            else:
                counts = Counter(
                    v for v in _col_strings(col) if v is not None)
            top = top_values_by_count(counts, top_k, min_support)
            vocab = {v: i for i, v in enumerate(top)}
            vocabs[f.name] = vocab
            for v in top:
                cols_meta.append(VectorColumnMeta(
                    f.name, f.kind.__name__, indicator_value=v))
            if self.get("track_other", True):
                cols_meta.append(VectorColumnMeta(
                    f.name, f.kind.__name__, indicator_value=OTHER_INDICATOR))
            if self.get("track_nulls", True):
                cols_meta.append(VectorColumnMeta(
                    f.name, f.kind.__name__, indicator_value=NULL_INDICATOR))
        meta = VectorMeta(self.output_name(), cols_meta)
        return self._finalize_model(OneHotModel(
            fitted={"vocabs": vocabs, "meta": meta}, **self.params))


class StringIndexerModel(TransformerModel):
    out_kind = RealNN
    is_device_op = False

    def transform(self, batch: ColumnBatch) -> Column:
        (f,) = self.input_features
        vocab = self.fitted["vocab"]
        strings = _col_strings(batch[f.name])
        handle = self.get("handle_invalid", "noFilter")
        unseen = len(vocab)
        ids = np.zeros(len(strings), np.int64)
        mask = np.ones(len(strings), bool)
        for i, v in enumerate(strings):
            if v is None or v not in vocab:
                if handle == "error" and v is not None:
                    raise ValueError(f"unseen label {v!r}")
                ids[i] = unseen
            else:
                ids[i] = vocab[v]
        return Column(RealNN, ids.astype(np.float32))


class StringIndexer(Estimator):
    """Text → ordinal index by descending frequency (≙ OpStringIndexer;
    'NoFilter' variant maps unseen to an extra bucket)."""

    out_kind = RealNN

    def __init__(self, handle_invalid: str = "noFilter", **params):
        super().__init__(handle_invalid=handle_invalid, **params)

    def fit(self, batch: ColumnBatch) -> TransformerModel:
        (f,) = self.input_features
        strings = _col_strings(batch[f.name])
        counts = Counter(v for v in strings if v is not None)
        # Spark orders by freq desc, then value asc
        ordered = sorted(counts.items(), key=lambda kv: (-kv[1], kv[0]))
        vocab = {v: i for i, (v, _) in enumerate(ordered)}
        model = StringIndexerModel(fitted={"vocab": vocab}, **self.params)
        model.metadata["labels"] = [v for v, _ in ordered]
        return self._finalize_model(model)


class IndexToString(Transformer):
    """Ordinal index → original label (≙ OpIndexToString)."""

    out_kind = Text
    is_device_op = False

    def __init__(self, labels: Sequence[str], **params):
        super().__init__(labels=list(labels), **params)

    def transform(self, batch: ColumnBatch) -> Column:
        (f,) = self.input_features
        labels = self.get("labels")
        ids = np.asarray(batch[f.name].values).astype(int)
        vals = np.array([labels[i] if 0 <= i < len(labels) else None
                         for i in ids], dtype=object)
        return Column(Text, vals)
