"""Map vectorizer — per-key expansion of all 25 map types (reference:
core/.../stages/impl/feature/OPMapVectorizer.scala, TextMapPivotVectorizer,
MultiPickListMapVectorizer, DateMapToUnitCircleVectorizer).

Fit discovers the key set (sorted, capped) and per-key statistics, then
dispatches on the map's value kind: numeric keys → fill+null-indicator,
categorical/text keys → top-K pivot, binary keys → 0/1+null, date keys →
unit circle, geolocation keys → mean-fill triple.
"""

from __future__ import annotations

from collections import Counter, defaultdict
from typing import Any, Dict, List

import jax.numpy as jnp
import numpy as np

from .categorical import top_values_by_count
from ..columns import Column, ColumnBatch
from ..stages.base import Estimator, TransformerModel
from ..types import (Binary, Date, DateTime, Geolocation, Integral,
                     MultiPickList, OPVector, Real, Text, is_numeric_kind,
                     map_value_kind)
from ..vector_meta import (NULL_INDICATOR, OTHER_INDICATOR, VectorColumnMeta,
                           VectorMeta)
from .dates import _MS_DAY, _period_fraction


def _map_values(col) -> List[Dict[str, Any]]:
    return [v if isinstance(v, dict) else {} for v in col.values]


def _numeric_map_arrays(exp, keys: List[str], fills: Dict[str, float]):
    """(vals [N, K] f32, presence [N, K] f32, fill vector [K]) in fitted-key
    order from a cached columnar expansion — the single source for both the
    eager and the staged numeric-map transform paths."""
    n = len(exp.nonempty)
    K = len(keys)
    idx = exp.key_index()
    vals_np = np.zeros((n, K), np.float32)
    pres_np = np.zeros((n, K), np.float32)
    for jj, k in enumerate(keys):
        j = idx.get(k)
        if j is not None:
            vals_np[:, jj] = exp.vals[:, j]
            pres_np[:, jj] = exp.present[:, j]
    fill_vec = np.asarray([fills.get(k, 0.0) for k in keys], np.float32)
    return vals_np, pres_np, fill_vec


def _fill_and_interleave(vd, pd, fill_vec, track_nulls: bool):
    """Device body shared by the eager and staged paths: fill absent values,
    optionally interleave [value, null] per key (matches the fitted meta)."""
    K = fill_vec.shape[0]
    filled = jnp.where(pd > 0, vd, jnp.asarray(fill_vec)[None, :])
    if not track_nulls:
        return filled
    return jnp.stack([filled, 1.0 - pd], axis=2).reshape(vd.shape[0], 2 * K)


class MapVectorizerModel(TransformerModel):
    out_kind = OPVector
    is_device_op = False
    supports_staging = True

    def transform_staged(self, batch: ColumnBatch):
        """Staged form for plain-numeric maps: host prologue pulls the
        cached columnar expansion (values + presence in fitted-key order);
        device body fills + interleaves null indicators — traceable, so the
        block fuses into the surrounding XLA program."""
        (f,) = self.input_features
        vk = map_value_kind(f.kind)
        if not (is_numeric_kind(vk) and not issubclass(vk, Binary)
                and not issubclass(vk, (Date, DateTime))):
            return None
        from .map_profile import map_expansion
        col = batch[f.name]
        if not col.is_host_object():
            return None
        exp = map_expansion(col)
        if exp is None:
            return None          # bool/mixed values: exact eager path
        keys: List[str] = self.fitted["keys"]
        track_nulls = self.get("track_nulls", True)
        K = len(keys)
        vals_np, pres_np, fill_vec = _numeric_map_arrays(
            exp, keys, self.fitted["fills"])
        meta = self.fitted["meta"]
        from ..columns import pack_bits, unpack_bits_device

        def body(w):
            vd = w["vals"]
            pd = unpack_bits_device(w["pres"], vd.shape[0] * K,
                                    (vd.shape[0], K)) if K else \
                jnp.zeros_like(vd)
            return Column(OPVector,
                          _fill_and_interleave(vd, pd, fill_vec, track_nulls),
                          meta=meta)

        return {"vals": vals_np, "pres": pack_bits(pres_np)}, body

    def transform(self, batch: ColumnBatch) -> Column:
        (f,) = self.input_features
        n = len(batch[f.name])
        vk = map_value_kind(f.kind)
        maps: List[Dict[str, Any]] = []
        if not (is_numeric_kind(vk) and not issubclass(vk, Binary)
                and not issubclass(vk, (Date, DateTime))):
            maps = _map_values(batch[f.name])
        keys: List[str] = self.fitted["keys"]
        track_nulls = self.get("track_nulls", True)
        blocks: List[np.ndarray] = []
        if issubclass(vk, Binary):
            for k in keys:
                col = np.zeros((n, 2 if track_nulls else 1), np.float32)
                for i, m in enumerate(maps):
                    v = m.get(k)
                    if v is None:
                        if track_nulls:
                            col[i, 1] = 1.0
                    else:
                        col[i, 0] = float(bool(v))
                blocks.append(col)
        elif issubclass(vk, (Date, DateTime)):
            periods = self.get("periods", ["HourOfDay", "DayOfWeek", "DayOfMonth", "DayOfYear"])
            for k in keys:
                vals = np.array([float(m.get(k) or 0) for m in maps])
                present = np.array([m.get(k) is not None for m in maps])
                cols = []
                for p in periods:
                    frac = np.asarray(_period_fraction(vals, p))
                    ang = 2 * np.pi * frac
                    cols.append(np.where(present, np.sin(ang), 0.0)[:, None])
                    cols.append(np.where(present, np.cos(ang), 0.0)[:, None])
                if track_nulls:
                    cols.append((~present).astype(np.float32)[:, None])
                blocks.append(np.concatenate(cols, axis=1).astype(np.float32))
        elif is_numeric_kind(vk):
            from .map_profile import map_expansion
            fills = self.fitted["fills"]
            exp = map_expansion(batch[f.name])
            if exp is not None:
                # cached one-pass columnar expansion, assembled on DEVICE:
                # the wire carries compact [N, K] values + presence instead
                # of a host-built [N, K·2] f32 block
                vals_np, pres_np, fill_vec = _numeric_map_arrays(
                    exp, keys, fills)
                from ..columns import to_device_f32
                vd = to_device_f32(vals_np)
                pd = to_device_f32(pres_np, exact=True)
                blocks.append(_fill_and_interleave(vd, pd, fill_vec,
                                                   track_nulls))
            else:
                if not maps:
                    maps = _map_values(batch[f.name])
                for k in keys:
                    fill = fills.get(k, 0.0)
                    col = np.zeros((n, 2 if track_nulls else 1), np.float32)
                    for i, m in enumerate(maps):
                        v = m.get(k)
                        if v is None:
                            col[i, 0] = fill
                            if track_nulls:
                                col[i, 1] = 1.0
                        else:
                            col[i, 0] = float(v)
                    blocks.append(col)
        elif issubclass(vk, MultiPickList):
            vocabs = self.fitted["vocabs"]
            for k in keys:
                vocab = vocabs.get(k, {})
                width = len(vocab) + 2
                col = np.zeros((n, width), np.float32)
                for i, m in enumerate(maps):
                    s = m.get(k)
                    if not s:
                        col[i, width - 1] = 1.0
                        continue
                    for v in s:
                        j = vocab.get(v)
                        if j is not None:
                            col[i, j] = 1.0
                        else:
                            col[i, len(vocab)] = 1.0
                blocks.append(col)
        elif issubclass(vk, Geolocation):
            fills = self.fitted["fills"]
            for k in keys:
                fill = np.asarray(fills.get(k, np.zeros(3)))
                col = np.zeros((n, 4 if track_nulls else 3), np.float32)
                for i, m in enumerate(maps):
                    v = m.get(k)
                    if v:
                        col[i, :3] = np.asarray(v[:3])
                    else:
                        col[i, :3] = fill
                        if track_nulls:
                            col[i, 3] = 1.0
                blocks.append(col)
        else:  # text-like → per-key top-K pivot
            vocabs = self.fitted["vocabs"]
            for k in keys:
                vocab = vocabs.get(k, {})
                width = len(vocab) + 2  # OTHER + null
                col = np.zeros((n, width), np.float32)
                for i, m in enumerate(maps):
                    v = m.get(k)
                    if v is None:
                        col[i, width - 1] = 1.0
                    else:
                        j = vocab.get(str(v), len(vocab))
                        col[i, j] = 1.0
                blocks.append(col)
        import jax
        if any(isinstance(b, jax.Array) for b in blocks):
            arr = (blocks[0] if len(blocks) == 1 else
                   jnp.concatenate([jnp.asarray(b) for b in blocks], axis=1))
            return Column(OPVector, arr, meta=self.fitted["meta"])
        arr = (np.concatenate(blocks, axis=1) if blocks
               else np.zeros((n, 0), np.float32))
        return Column(OPVector, jnp.asarray(arr), meta=self.fitted["meta"])


class MapVectorizer(Estimator):
    """Per-key expansion of a map feature (≙ OPMapVectorizer.scala)."""

    out_kind = OPVector

    def __init__(self, top_k: int = 20, min_support: int = 10,
                 track_nulls: bool = True, max_keys: int = 100,
                 allow_list: List[str] = None, block_list: List[str] = None,
                 **params):
        super().__init__(top_k=top_k, min_support=min_support,
                         track_nulls=track_nulls, max_keys=max_keys,
                         allow_list=allow_list, block_list=block_list, **params)

    def fit(self, batch: ColumnBatch) -> TransformerModel:
        (f,) = self.input_features
        vk = map_value_kind(f.kind)
        exp = None
        numeric_plain = (is_numeric_kind(vk) and not issubclass(vk, Binary)
                         and not issubclass(vk, (Date, DateTime)))
        if numeric_plain:
            from .map_profile import map_expansion
            exp = map_expansion(batch[f.name])
        maps = [] if exp is not None else _map_values(batch[f.name])
        allow = self.get("allow_list")
        block = set(self.get("block_list") or ())
        if exp is not None:
            # in_dict replicates Counter(m.keys()); most_common's stable
            # descending order = sort by (-count, first-occurrence)
            order = sorted(range(len(exp.keys)),
                           key=lambda j: (-int(exp.in_dict[j]), j))
            top = [exp.keys[j] for j in order[:self.get("max_keys")]]
        else:
            key_counts: Counter = Counter()
            for m in maps:
                key_counts.update(m.keys())
            top = [k for k, _ in
                   key_counts.most_common(self.get("max_keys"))]
        keys = sorted(k for k in top
                      if (allow is None or k in allow) and k not in block)
        fitted: Dict[str, Any] = {"keys": keys}
        cols_meta: List[VectorColumnMeta] = []
        tn = self.get("track_nulls", True)
        kindname = f.kind.__name__
        if issubclass(vk, Binary):
            for k in keys:
                cols_meta.append(VectorColumnMeta(f.name, kindname, grouping=k))
                if tn:
                    cols_meta.append(VectorColumnMeta(
                        f.name, kindname, grouping=k, indicator_value=NULL_INDICATOR))
        elif issubclass(vk, (Date, DateTime)):
            periods = ["HourOfDay", "DayOfWeek", "DayOfMonth", "DayOfYear"]
            self.set("periods", periods)
            for k in keys:
                for p in periods:
                    cols_meta.append(VectorColumnMeta(
                        f.name, kindname, grouping=k, descriptor_value=f"sin({p})"))
                    cols_meta.append(VectorColumnMeta(
                        f.name, kindname, grouping=k, descriptor_value=f"cos({p})"))
                if tn:
                    cols_meta.append(VectorColumnMeta(
                        f.name, kindname, grouping=k, indicator_value=NULL_INDICATOR))
        elif is_numeric_kind(vk):
            fills: Dict[str, float] = {}
            idx = exp.key_index() if exp is not None else {}
            for k in keys:
                if exp is not None:
                    j = idx.get(k)
                    pres = (exp.present[:, j] if j is not None
                            else np.zeros(0, bool))
                    fills[k] = (float(exp.vals[pres, j].mean())
                                if j is not None and pres.any() else 0.0)
                else:
                    vals = [float(m[k]) for m in maps if m.get(k) is not None]
                    fills[k] = float(np.mean(vals)) if vals else 0.0
                cols_meta.append(VectorColumnMeta(f.name, kindname, grouping=k))
                if tn:
                    cols_meta.append(VectorColumnMeta(
                        f.name, kindname, grouping=k, indicator_value=NULL_INDICATOR))
            fitted["fills"] = fills
        elif issubclass(vk, MultiPickList):
            vocabs: Dict[str, Dict[str, int]] = {}
            for k in keys:
                cnt = Counter()
                for m in maps:
                    for v in (m.get(k) or ()):
                        cnt[v] += 1
                top = top_values_by_count(cnt, self.get("top_k"),
                                          self.get("min_support"))
                vocab = {v: i for i, v in enumerate(top)}
                vocabs[k] = vocab
                for v in top:
                    cols_meta.append(VectorColumnMeta(
                        f.name, kindname, grouping=k, indicator_value=v))
                cols_meta.append(VectorColumnMeta(
                    f.name, kindname, grouping=k, indicator_value=OTHER_INDICATOR))
                cols_meta.append(VectorColumnMeta(
                    f.name, kindname, grouping=k, indicator_value=NULL_INDICATOR))
            fitted["vocabs"] = vocabs
        elif issubclass(vk, Geolocation):
            fills = {}
            for k in keys:
                vals = [list(m[k])[:3] for m in maps if m.get(k)]
                # plain float lists: fitted nested dicts must stay JSON-safe
                fills[k] = ([float(x) for x in
                             np.mean(np.asarray(vals, np.float32), axis=0)]
                            if vals else [0.0, 0.0, 0.0])
                for d in ("lat", "lon", "accuracy"):
                    cols_meta.append(VectorColumnMeta(
                        f.name, kindname, grouping=k, descriptor_value=d))
                if tn:
                    cols_meta.append(VectorColumnMeta(
                        f.name, kindname, grouping=k, indicator_value=NULL_INDICATOR))
            fitted["fills"] = fills
        else:
            vocabs = {}
            for k in keys:
                cnt = Counter(str(m[k]) for m in maps if m.get(k) is not None)
                top = top_values_by_count(cnt, self.get("top_k"),
                                          self.get("min_support"))
                vocab = {v: i for i, v in enumerate(top)}
                vocabs[k] = vocab
                for v in top:
                    cols_meta.append(VectorColumnMeta(
                        f.name, kindname, grouping=k, indicator_value=v))
                cols_meta.append(VectorColumnMeta(
                    f.name, kindname, grouping=k, indicator_value=OTHER_INDICATOR))
                cols_meta.append(VectorColumnMeta(
                    f.name, kindname, grouping=k, indicator_value=NULL_INDICATOR))
            fitted["vocabs"] = vocabs
        fitted["meta"] = VectorMeta(self.output_name(), cols_meta)
        return self._finalize_model(MapVectorizerModel(fitted=fitted, **self.params))
