"""Specialized text stages — the TPU-native re-design of the reference's
Lucene/OpenNLP/Tika/libphonenumber-backed feature family (reference:
core/.../stages/impl/feature/PhoneNumberParser.scala:143-258,
ValidEmailTransformer.scala:41, EmailToPickListMapTransformer.scala:40,
UrlMapToPickListMapTransformer.scala:40, MimeTypeDetector.scala:49-126,
OpCountVectorizer.scala:44, OpNGram.scala:52, OpStopWordsRemover.scala:48,
NGramSimilarity.scala:46-99, JaccardSimilarity.scala:40, LangDetector.scala:46,
NameEntityRecognizer.scala:56, HumanNameDetector.scala:56-118,
OpLDA.scala:41, OpWord2Vec.scala:41).

TPU design: string parsing/validation is a host-side vectorized prologue
(strings never reach the device — same split as ops/text.py); the numeric
products (count matrices, topic mixtures, embeddings) are device arrays, and
the LDA / Word2Vec training loops are jitted XLA programs (`lax.fori_loop`
over full-batch multiplicative updates / negative-sampling SGD steps) instead
of the reference's Spark MLlib wrappers.  Heavy external engines
(libphonenumber, Tika, Optimaize, OpenNLP) are replaced by compact built-in
tables: country calling-code metadata, magic-byte MIME signatures, per-language
stop-word profiles, and name/gender dictionaries.
"""

from __future__ import annotations

import base64
import binascii
import bisect
import functools
import re
from collections import Counter
from typing import Dict, List, Optional, Sequence, Set, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..columns import Column, ColumnBatch
from ..stages.base import Estimator, Transformer, TransformerModel
from ..types import (Base64, Base64Map, Binary, BinaryMap, Email, EmailMap,
                     MultiPickList, MultiPickListMap, OPVector, Phone,
                     PhoneMap, PickList, PickListMap, Real, RealMap, RealNN,
                     Text, TextList, URL, URLMap)
from ..vector_meta import VectorColumnMeta, VectorMeta
from .categorical import _col_strings

# ---------------------------------------------------------------------------
# Email / URL validation
# ---------------------------------------------------------------------------

def email_parts(s: Optional[str]) -> Tuple[Optional[str], Optional[str]]:
    """(prefix, domain) of an email, Nones when invalid — delegates to the
    Email type accessors (types.py) for one set of semantics (≙ Email.prefix /
    Email.domain, features/.../types/Text.scala)."""
    if not s:
        return None, None
    e = Email(s)
    return e.prefix(), e.domain()


def url_domain(s: Optional[str]) -> Optional[str]:
    """Host of a valid http/https/ftp URL else None — delegates to the URL
    type accessors (≙ URL.domain/isValid, features/.../types/Text.scala:191)."""
    if not s:
        return None
    u = URL(s)
    return u.domain() if u.is_valid() else None


class ValidEmailTransformer(Transformer):
    """Email → Binary validity (≙ ValidEmailTransformer.scala:41: empty →
    empty Binary, else prefix and domain both non-empty)."""

    in_kinds = (Email,)
    out_kind = Binary
    is_device_op = False

    def transform(self, batch: ColumnBatch) -> Column:
        (f,) = self.input_features
        strings = _col_strings(batch[f.name])
        vals = np.zeros(len(strings), np.float32)
        mask = np.zeros(len(strings), bool)
        for i, s in enumerate(strings):
            if s is None:
                continue
            mask[i] = True
            p, d = email_parts(s)
            vals[i] = 1.0 if (p and d) else 0.0
        return Column(Binary, vals, mask=mask)


class EmailToPickListTransformer(Transformer):
    """Email → PickList of the domain (≙ EmailToPickListMapTransformer's inner
    EmailToPickList, EmailToPickListMapTransformer.scala:50-52)."""

    in_kinds = (Email,)
    out_kind = PickList
    is_device_op = False

    def transform(self, batch: ColumnBatch) -> Column:
        (f,) = self.input_features
        strings = _col_strings(batch[f.name])
        out = np.empty(len(strings), object)
        for i, s in enumerate(strings):
            _, d = email_parts(s)
            out[i] = d
        return Column(PickList, out)


class UrlToPickListTransformer(Transformer):
    """URL → PickList of the domain of a valid url (≙ the Transmogrifier's
    TextTransmogrify url case: url.toDomain, Transmogrifier.scala:116-180)."""

    in_kinds = (URL,)
    out_kind = PickList
    is_device_op = False

    def transform(self, batch: ColumnBatch) -> Column:
        (f,) = self.input_features
        strings = _col_strings(batch[f.name])
        out = np.empty(len(strings), object)
        for i, s in enumerate(strings):
            out[i] = url_domain(s)
        return Column(PickList, out)


class EmailMapToPickListMapTransformer(Transformer):
    """EmailMap → PickListMap of per-key domains (≙
    EmailToPickListMapTransformer.scala:40)."""

    in_kinds = (EmailMap,)
    out_kind = PickListMap
    is_device_op = False

    def transform(self, batch: ColumnBatch) -> Column:
        (f,) = self.input_features
        out = np.empty(len(batch), object)
        for i, m in enumerate(batch[f.name].values):
            m = m if isinstance(m, dict) else {}
            res = {}
            for k, v in m.items():
                _, d = email_parts(v)
                if d:
                    res[k] = d
            out[i] = res
        return Column(PickListMap, out)


class UrlMapToPickListMapTransformer(Transformer):
    """URLMap → PickListMap of per-key domains of *valid* urls (≙
    UrlMapToPickListMapTransformer.scala:40-44)."""

    in_kinds = (URLMap,)
    out_kind = PickListMap
    is_device_op = False

    def transform(self, batch: ColumnBatch) -> Column:
        (f,) = self.input_features
        out = np.empty(len(batch), object)
        for i, m in enumerate(batch[f.name].values):
            m = m if isinstance(m, dict) else {}
            res = {}
            for k, v in m.items():
                d = url_domain(v)
                if d:
                    res[k] = d
            out[i] = res
        return Column(PickListMap, out)


# ---------------------------------------------------------------------------
# Phone validation (≙ PhoneNumberParser.scala; libphonenumber replaced by a
# compact calling-code → national-number-length metadata table)
#
# Deliberate v1 trade-off: validation is LENGTH-ONLY per region/calling code.
# Unlike libphonenumber (the reference's 566-LoC wrapper + full metadata), we
# do not model digit-pattern rules, so these classes FALSE-ACCEPT:
#   * all-zero / reserved national numbers of a valid length
#     ("+1 000 000 0000" validates; libphonenumber rejects it),
#   * NANP numbers whose area code starts with 0/1,
#   * numbers in unlisted regions passed internationally with ``strict=False``
#     (any 4-15 digits after an unknown '+<cc>' are accepted, per E.164 shape).
# Rejections (wrong length for the matched calling code / default region,
# non-digit garbage, unknown default region) are reliable.  The envelope is
# pinned by tests/test_text_specialized.py::test_phone_validation_envelope.
# ---------------------------------------------------------------------------

# region → (calling code, min national digits, max national digits)
PHONE_REGIONS: Dict[str, Tuple[str, int, int]] = {
    "US": ("1", 10, 10), "CA": ("1", 10, 10), "GB": ("44", 9, 10),
    "FR": ("33", 9, 9), "DE": ("49", 6, 11), "ES": ("34", 9, 9),
    "IT": ("39", 8, 11), "NL": ("31", 9, 9), "BR": ("55", 10, 11),
    "MX": ("52", 10, 10), "IN": ("91", 10, 10), "CN": ("86", 10, 11),
    "JP": ("81", 9, 10), "KR": ("82", 8, 10), "AU": ("61", 9, 9),
    "RU": ("7", 10, 10), "ZA": ("27", 9, 9), "NG": ("234", 7, 10),
    "AR": ("54", 10, 10), "CL": ("56", 8, 9), "CO": ("57", 10, 10),
    "PE": ("51", 8, 9), "SE": ("46", 7, 9), "NO": ("47", 8, 8),
    "DK": ("45", 8, 8), "FI": ("358", 5, 10), "PL": ("48", 9, 9),
    "PT": ("351", 9, 9), "GR": ("30", 10, 10), "TR": ("90", 10, 10),
    "IL": ("972", 8, 9), "SA": ("966", 8, 9), "AE": ("971", 8, 9),
    "SG": ("65", 8, 8), "MY": ("60", 7, 10), "TH": ("66", 8, 9),
    "VN": ("84", 9, 10), "PH": ("63", 8, 10), "ID": ("62", 7, 11),
    "NZ": ("64", 8, 9), "IE": ("353", 7, 9), "CH": ("41", 9, 9),
    "AT": ("43", 4, 13), "BE": ("32", 8, 9), "CZ": ("420", 9, 9),
    "UA": ("380", 9, 9), "EG": ("20", 8, 10), "KE": ("254", 9, 9),
    "PK": ("92", 9, 10), "BD": ("880", 6, 10), "HK": ("852", 8, 8),
}

_CC_TO_RANGE: Dict[str, Tuple[int, int]] = {}
for _r, (_cc, _lo, _hi) in PHONE_REGIONS.items():
    lo, hi = _CC_TO_RANGE.get(_cc, (_lo, _hi))
    _CC_TO_RANGE[_cc] = (min(lo, _lo), max(hi, _hi))
_CCS_BY_LEN = sorted(_CC_TO_RANGE, key=len, reverse=True)

DEFAULT_REGION = "US"


def clean_phone_number(s: str) -> str:
    """Strip everything but digits and a leading '+'
    (≙ PhoneNumberParser.cleanNumber, PhoneNumberParser.scala:267)."""
    s = s.strip()
    plus = s.startswith("+")
    digits = re.sub(r"\D", "", s)
    return ("+" + digits) if plus else digits


def parse_phone(s: Optional[str], region: str = DEFAULT_REGION,
                strict: bool = False) -> Optional[str]:
    """→ E.164-ish '+<cc><national>' when valid, else None
    (≙ PhoneNumberParser.parse/validate, PhoneNumberParser.scala:270-320).
    International format (leading '+') is matched against known calling codes;
    otherwise the default region's metadata applies.  ``strict`` requires an
    exact length match even for international numbers with unknown codes."""
    if not s:
        return None
    cleaned = clean_phone_number(s)
    if cleaned.startswith("+"):
        digits = cleaned[1:]
        for cc in _CCS_BY_LEN:
            if digits.startswith(cc):
                lo, hi = _CC_TO_RANGE[cc]
                nat = digits[len(cc):]
                if lo <= len(nat) <= hi:
                    return "+" + digits
                return None
        return None if strict else ("+" + digits if 4 <= len(digits) <= 15 else None)
    meta = PHONE_REGIONS.get(region.upper())
    if meta is None:
        return None
    cc, lo, hi = meta
    digits = cleaned
    # national numbers sometimes carry the country code already
    if len(digits) > hi and digits.startswith(cc) and lo <= len(digits) - len(cc) <= hi:
        return "+" + digits
    if lo <= len(digits) <= hi:
        return "+" + cc + digits
    return None


class _PhoneParamsMixin:
    """≙ PhoneParams/PhoneCountryParams (PhoneNumberParser.scala:56-119)."""

    def set_default_region(self, cc: str):
        self.set("default_region", cc)
        return self

    def set_strictness(self, flag: bool):
        self.set("strict_validation", flag)
        return self


class ParsePhoneDefaultCountry(_PhoneParamsMixin, Transformer):
    """Phone → normalized E.164 Phone (≙ ParsePhoneDefaultCountry,
    PhoneNumberParser.scala:170-180)."""

    in_kinds = (Phone,)
    out_kind = Phone
    is_device_op = False

    def __init__(self, default_region: str = DEFAULT_REGION,
                 strict_validation: bool = False, **params):
        super().__init__(default_region=default_region,
                         strict_validation=strict_validation, **params)

    def transform(self, batch: ColumnBatch) -> Column:
        (f,) = self.input_features
        strings = _col_strings(batch[f.name])
        out = np.empty(len(strings), object)
        for i, s in enumerate(strings):
            out[i] = parse_phone(s, self.get("default_region", DEFAULT_REGION),
                                 self.get("strict_validation", False))
        return Column(Phone, out)


class IsValidPhoneDefaultCountry(_PhoneParamsMixin, Transformer):
    """Phone → Binary validity (≙ IsValidPhoneDefaultCountry,
    PhoneNumberParser.scala:225-238)."""

    in_kinds = (Phone,)
    out_kind = Binary
    is_device_op = False

    def __init__(self, default_region: str = DEFAULT_REGION,
                 strict_validation: bool = False, **params):
        super().__init__(default_region=default_region,
                         strict_validation=strict_validation, **params)

    def transform(self, batch: ColumnBatch) -> Column:
        (f,) = self.input_features
        strings = _col_strings(batch[f.name])
        vals = np.zeros(len(strings), np.float32)
        mask = np.zeros(len(strings), bool)
        for i, s in enumerate(strings):
            if s is None:
                continue
            mask[i] = True
            ok = parse_phone(s, self.get("default_region", DEFAULT_REGION),
                             self.get("strict_validation", False))
            vals[i] = 1.0 if ok else 0.0
        return Column(Binary, vals, mask=mask)


class IsValidPhoneMapDefaultCountry(_PhoneParamsMixin, Transformer):
    """PhoneMap → BinaryMap of per-key validity (≙ IsValidPhoneMapDefaultCountry,
    PhoneNumberParser.scala:241-251)."""

    in_kinds = (PhoneMap,)
    out_kind = BinaryMap
    is_device_op = False

    def __init__(self, default_region: str = DEFAULT_REGION,
                 strict_validation: bool = False, **params):
        super().__init__(default_region=default_region,
                         strict_validation=strict_validation, **params)

    def transform(self, batch: ColumnBatch) -> Column:
        (f,) = self.input_features
        region = self.get("default_region", DEFAULT_REGION)
        strict = self.get("strict_validation", False)
        out = np.empty(len(batch), object)
        for i, m in enumerate(batch[f.name].values):
            m = m if isinstance(m, dict) else {}
            out[i] = {k: bool(parse_phone(v, region, strict))
                      for k, v in m.items() if v is not None}
        return Column(BinaryMap, out)


# ---------------------------------------------------------------------------
# MIME detection on Base64 (≙ MimeTypeDetector.scala; Tika replaced by
# magic-byte signatures)
# ---------------------------------------------------------------------------

_MAGIC: List[Tuple[bytes, str]] = [
    (b"\xff\xd8\xff", "image/jpeg"),
    (b"\x89PNG\r\n\x1a\n", "image/png"),
    (b"GIF87a", "image/gif"), (b"GIF89a", "image/gif"),
    (b"BM", "image/bmp"),
    (b"II*\x00", "image/tiff"), (b"MM\x00*", "image/tiff"),
    (b"%PDF", "application/pdf"),
    (b"PK\x03\x04", "application/zip"),
    (b"\x1f\x8b", "application/gzip"),
    (b"Rar!\x1a\x07", "application/x-rar-compressed"),
    (b"7z\xbc\xaf\x27\x1c", "application/x-7z-compressed"),
    (b"ID3", "audio/mpeg"), (b"\xff\xfb", "audio/mpeg"),
    (b"OggS", "audio/ogg"),
    (b"fLaC", "audio/flac"),
    (b"\x00\x00\x00\x18ftyp", "video/mp4"), (b"\x00\x00\x00\x20ftyp", "video/mp4"),
    (b"\x1aE\xdf\xa3", "video/webm"),
    (b"\xd0\xcf\x11\xe0\xa1\xb1\x1a\xe1", "application/x-ole-storage"),
    (b"{\\rtf", "application/rtf"),
    (b"MZ", "application/x-msdownload"),
    (b"\x7fELF", "application/x-elf"),
]


def detect_mime(data: bytes, type_hint: str = "") -> str:
    """Magic-byte MIME sniffing (≙ MimeTypeDetector.detect,
    MimeTypeDetector.scala:111-126).  ``type_hint`` wins when supplied, like
    Tika's CONTENT_TYPE hint."""
    if type_hint:
        return type_hint
    if data.startswith(b"RIFF") and len(data) >= 12:
        sub = data[8:12]
        if sub == b"WAVE":
            return "audio/x-wav"
        if sub == b"AVI ":
            return "video/x-msvideo"
        if sub == b"WEBP":
            return "image/webp"
    for sig, mime in _MAGIC:
        if data.startswith(sig):
            return mime
    head = data[:512].lstrip()
    low = head[:64].lower()
    if low.startswith(b"<?xml"):
        return "application/xml"
    if low.startswith(b"<!doctype html") or low.startswith(b"<html"):
        return "text/html"
    if not data:
        return "application/octet-stream"
    try:
        head.decode("utf-8")
        return "text/plain"
    except UnicodeDecodeError as e:
        # tolerate ONLY a genuine multi-byte char split by truncation: the
        # failing byte must be a UTF-8 lead byte whose continuation would
        # extend past the (cut) end — not just any junk near the end
        b0 = head[e.start]
        need = (2 if 0xC2 <= b0 <= 0xDF else 3 if 0xE0 <= b0 <= 0xEF
                else 4 if 0xF0 <= b0 <= 0xF4 else 0)
        if need and e.start + need > len(head) and e.start >= len(head) - 3:
            return "text/plain"
        return "application/octet-stream"


def _b64_bytes(s: Optional[str], max_bytes: int) -> Optional[bytes]:
    if s is None:
        return None
    # cut must stay a multiple of 4 so the truncated prefix is decodable
    cut = ((max_bytes + 2) // 3) * 4
    try:
        return base64.b64decode(s[:cut], validate=False)[:max_bytes]
    except (binascii.Error, ValueError):
        return b""


class MimeTypeDetector(Transformer):
    """Base64 → Text MIME type (≙ MimeTypeDetector.scala:49-57)."""

    in_kinds = (Base64,)
    out_kind = Text
    is_device_op = False

    def __init__(self, type_hint: str = "", max_bytes_to_parse: int = 1024,
                 **params):
        super().__init__(type_hint=type_hint,
                         max_bytes_to_parse=max_bytes_to_parse, **params)

    def transform(self, batch: ColumnBatch) -> Column:
        (f,) = self.input_features
        strings = _col_strings(batch[f.name])
        out = np.empty(len(strings), object)
        hint = self.get("type_hint", "")
        mx = int(self.get("max_bytes_to_parse", 1024))
        for i, s in enumerate(strings):
            data = _b64_bytes(s, mx)
            out[i] = None if data is None else detect_mime(data, hint)
        return Column(Text, out)


class MimeTypeMapDetector(Transformer):
    """Base64Map → PickListMap of per-key MIME types (≙
    MimeTypeDetector.scala:61-70)."""

    in_kinds = (Base64Map,)
    out_kind = PickListMap
    is_device_op = False

    def __init__(self, type_hint: str = "", max_bytes_to_parse: int = 1024,
                 **params):
        super().__init__(type_hint=type_hint,
                         max_bytes_to_parse=max_bytes_to_parse, **params)

    def transform(self, batch: ColumnBatch) -> Column:
        (f,) = self.input_features
        hint = self.get("type_hint", "")
        mx = int(self.get("max_bytes_to_parse", 1024))
        out = np.empty(len(batch), object)
        for i, m in enumerate(batch[f.name].values):
            m = m if isinstance(m, dict) else {}
            res = {}
            for k, v in m.items():
                data = _b64_bytes(v, mx)
                if data is not None:
                    res[k] = detect_mime(data, hint)
            out[i] = res
        return Column(PickListMap, out)


# ---------------------------------------------------------------------------
# CountVectorizer / NGram / StopWordsRemover (≙ Spark ML wrappers
# OpCountVectorizer.scala, OpNGram.scala, OpStopWordsRemover.scala)
# ---------------------------------------------------------------------------

class CountVectorizerModel(TransformerModel):
    out_kind = OPVector
    is_device_op = False

    def transform(self, batch: ColumnBatch) -> Column:
        (f,) = self.input_features
        vocab: Dict[str, int] = {t: i for i, t in enumerate(self.fitted["vocab"])}
        n = len(batch)
        width = len(vocab)
        arr = np.zeros((n, width), np.float32)
        min_tf = float(self.get("min_tf", 1.0))
        binary = self.get("binary", False)
        for i, toks in enumerate(batch[f.name].values):
            if not toks:
                continue
            counts = Counter(t for t in toks if t in vocab)
            # minTF: per-document filter — fraction when < 1, else absolute
            thresh = min_tf * len(toks) if min_tf < 1.0 else min_tf
            for t, c in counts.items():
                if c >= thresh:
                    arr[i, vocab[t]] = 1.0 if binary else float(c)
        return Column(OPVector, jnp.asarray(arr), meta=self.fitted["meta"])


class OpCountVectorizer(Estimator):
    """TextList → count vector over a learned vocabulary (≙
    OpCountVectorizer.scala:44-121; Spark CountVectorizer semantics: vocab =
    top ``vocab_size`` terms with document frequency ≥ ``min_df``)."""

    in_kinds = (TextList,)
    out_kind = OPVector

    def __init__(self, vocab_size: int = 512, min_df: float = 1.0,
                 min_tf: float = 1.0, binary: bool = False, **params):
        super().__init__(vocab_size=vocab_size, min_df=min_df, min_tf=min_tf,
                         binary=binary, **params)

    def fit(self, batch: ColumnBatch) -> TransformerModel:
        (f,) = self.input_features
        df_counts: Counter = Counter()
        tf_counts: Counter = Counter()
        n_docs = 0
        for toks in batch[f.name].values:
            if toks is None:
                continue
            n_docs += 1
            c = Counter(toks)
            tf_counts.update(c)
            df_counts.update(c.keys())
        min_df = float(self.get("min_df", 1.0))
        df_thresh = min_df * n_docs if min_df < 1.0 else min_df
        eligible = [t for t, d in df_counts.items() if d >= df_thresh]
        # top-vocab_size by total term frequency, ties broken lexicographically
        eligible.sort(key=lambda t: (-tf_counts[t], t))
        vocab = sorted(eligible[: int(self.get("vocab_size", 512))])
        cols = [VectorColumnMeta(f.name, f.kind.__name__, indicator_value=t)
                for t in vocab]
        meta = VectorMeta(self.output_name(), cols)
        return self._finalize_model(CountVectorizerModel(
            fitted={"vocab": vocab, "meta": meta}, **self.params))


class OpNGram(Transformer):
    """TextList → TextList of space-joined n-grams (≙ OpNGram.scala:52,
    Spark NGram semantics: fewer than n tokens → empty list)."""

    in_kinds = (TextList,)
    out_kind = TextList
    is_device_op = False

    def __init__(self, n: int = 2, **params):
        super().__init__(n=n, **params)

    def transform(self, batch: ColumnBatch) -> Column:
        (f,) = self.input_features
        n = int(self.get("n", 2))
        out = np.empty(len(batch), object)
        for i, toks in enumerate(batch[f.name].values):
            toks = toks or []
            out[i] = [" ".join(toks[j:j + n]) for j in range(len(toks) - n + 1)]
        return Column(TextList, out)


# Spark ML's english stop-word list (StopWordsRemover.loadDefaultStopWords)
ENGLISH_STOP_WORDS: Set[str] = set("""a about above after again against all am
an and any are aren't as at be because been before being below between both
but by can't cannot could couldn't did didn't do does doesn't doing don't down
during each few for from further had hadn't has hasn't have haven't having he
he'd he'll he's her here here's hers herself him himself his how how's i i'd
i'll i'm i've if in into is isn't it it's its itself let's me more most
mustn't my myself no nor not of off on once only or other ought our ours
ourselves out over own same shan't she she'd she'll she's should shouldn't so
some such than that that's the their theirs them themselves then there there's
these they they'd they'll they're they've this those through to too under
until up very was wasn't we we'd we'll we're we've were weren't what what's
when when's where where's which while who who's whom why why's with won't
would wouldn't you you'd you'll you're you've your yours yourself
yourselves""".split())


class OpStopWordsRemover(Transformer):
    """TextList → TextList minus stop words (≙ OpStopWordsRemover.scala:48)."""

    in_kinds = (TextList,)
    out_kind = TextList
    is_device_op = False

    def __init__(self, stop_words: Optional[Sequence[str]] = None,
                 case_sensitive: bool = False, **params):
        super().__init__(stop_words=list(stop_words) if stop_words else None,
                         case_sensitive=case_sensitive, **params)

    def transform(self, batch: ColumnBatch) -> Column:
        (f,) = self.input_features
        words = self.get("stop_words") or ENGLISH_STOP_WORDS
        cs = self.get("case_sensitive", False)
        stop = set(words) if cs else {w.lower() for w in words}
        out = np.empty(len(batch), object)
        for i, toks in enumerate(batch[f.name].values):
            toks = toks or []
            out[i] = [t for t in toks
                      if (t if cs else t.lower()) not in stop]
        return Column(TextList, out)


# ---------------------------------------------------------------------------
# N-gram / Jaccard similarity (≙ NGramSimilarity.scala, JaccardSimilarity.scala)
# ---------------------------------------------------------------------------

def ngram_distance(source: str, target: str, n: int = 3) -> float:
    """Lucene NGramDistance: n-gram-windowed edit similarity in [0, 1].

    The row recurrence ``cur[i] = min(cur[i-1]+1, prev[i]+1, prev[i-1]+ec)``
    vectorizes per target position: with ``b[i] = min(prev[i]+1, prev[i-1]+ec)``
    the left-neighbor term is ``min_k<=i (b[k] + (i-k))``, a cumulative min of
    ``b - i`` — so each row is O(sl) numpy instead of a Python inner loop."""
    sl, tl = len(source), len(target)
    if sl == 0 or tl == 0:
        return 1.0 if sl == tl else 0.0
    if sl < n or tl < n:
        matches = sum(1 for a, b in zip(source, target) if a == b)
        return matches / max(sl, tl)
    # source padded with n-1 sentinel chars; [sl, n] sliding n-gram windows
    sa = np.frombuffer(("\0" * (n - 1) + source).encode("utf-32-le"),
                       dtype=np.uint32)
    windows = np.lib.stride_tricks.sliding_window_view(sa, n)
    tgt = np.frombuffer(("\0" * (n - 1) + target).encode("utf-32-le"),
                        dtype=np.uint32)
    idx = np.arange(sl + 1, dtype=np.float64)
    prev = idx.copy()
    for j in range(1, tl + 1):
        t_j = tgt[j - 1:j - 1 + n]
        neq = windows != t_j
        cost = neq.sum(axis=1)
        # sentinel-prefix matches don't count toward the gram length
        tn = n - ((~neq) & (windows == 0)).sum(axis=1)
        ec = cost / tn
        b = np.empty(sl + 1, dtype=np.float64)
        b[0] = j
        np.minimum(prev[1:] + 1.0, prev[:-1] + ec, out=b[1:])
        prev = idx + np.minimum.accumulate(b - idx)
    return float(1.0 - prev[sl] / max(sl, tl))


class TextNGramSimilarity(Transformer):
    """(Text, Text) → RealNN n-gram similarity (≙ TextNGramSimilarity,
    NGramSimilarity.scala:62-99; either side empty → 0.0)."""

    in_kinds = (Text, Text)
    out_kind = RealNN
    is_device_op = False

    def __init__(self, ngram_size: int = 3, to_lowercase: bool = True, **params):
        super().__init__(ngram_size=ngram_size, to_lowercase=to_lowercase,
                         **params)

    def _to_string(self, v) -> str:
        if v is None:
            return ""
        if isinstance(v, (frozenset, set, list, tuple)):
            return " ".join(sorted(str(x) for x in v))
        return str(v)

    def transform(self, batch: ColumnBatch) -> Column:
        f1, f2 = self.input_features
        a = batch[f1.name].values
        b = batch[f2.name].values
        lc = self.get("to_lowercase", True)
        nsz = int(self.get("ngram_size", 3))
        vals = np.zeros(len(batch), np.float32)
        for i in range(len(batch)):
            s1, s2 = self._to_string(a[i]).strip(), self._to_string(b[i]).strip()
            if lc:
                s1, s2 = s1.lower(), s2.lower()
            vals[i] = 0.0 if (not s1 or not s2) else ngram_distance(s1, s2, nsz)
        return Column(RealNN, vals)


class SetNGramSimilarity(TextNGramSimilarity):
    """(MultiPickList, MultiPickList) → RealNN (≙ SetNGramSimilarity,
    NGramSimilarity.scala:46: sets joined to strings first)."""

    in_kinds = (MultiPickList, MultiPickList)


class JaccardSimilarity(Transformer):
    """(MultiPickList, MultiPickList) → RealNN |∩|/|∪|; both empty → 1.0
    (≙ JaccardSimilarity.scala:40-47)."""

    in_kinds = (MultiPickList, MultiPickList)
    out_kind = RealNN
    is_device_op = False

    def transform(self, batch: ColumnBatch) -> Column:
        f1, f2 = self.input_features
        a = batch[f1.name].values
        b = batch[f2.name].values
        vals = np.zeros(len(batch), np.float32)
        for i in range(len(batch)):
            x = set(a[i] or ())
            y = set(b[i] or ())
            if not x and not y:
                vals[i] = 1.0
            else:
                vals[i] = len(x & y) / len(x | y)
        return Column(RealNN, vals)


# ---------------------------------------------------------------------------
# Language detection (≙ LangDetector.scala + the 69-language enum at
# utils/.../text/LanguageDetector.scala:59; Optimaize replaced by Unicode
# script analysis + per-language stop-word profiles)
#
# Two signals, like the reference's n-gram detector effectively combines:
#   1. the SCRIPT a character belongs to (Hangul → ko, Thai → th, ...) — for
#      single-language scripts this alone seals the call, and for
#      script-families (Latin, Cyrillic, Arabic, Devanagari, Hebrew, Han)
#      it restricts the candidate set;
#   2. stop-word profile hit rates WITHIN the candidate set (space-separated
#      scripts), or distinctive-character counts for Han (simplified vs
#      traditional Chinese, kana → Japanese).
# ---------------------------------------------------------------------------

def _lang_profiles() -> Dict[str, Set[str]]:
    """Packaged per-language stop-word profiles (67 languages) — loaded from
    the resources module, the analog of Optimaize's language profiles shipped
    in the reference's models module (see resources/__init__.py)."""
    from ..resources import lang_profiles
    return lang_profiles()


_WORD_RE = re.compile(r"[^\W\d_]+", re.UNICODE)

# script → (unicode ranges, candidate languages); None candidates = resolved
# via the word profiles of the family
_SCRIPTS: Dict[str, Tuple[Tuple[Tuple[int, int], ...], Tuple[str, ...]]] = {
    "latin": (((0x41, 0x5A), (0x61, 0x7A), (0xC0, 0x24F),
               (0x1E00, 0x1EFF)), ()),          # profiles decide
    "cyrillic": (((0x400, 0x4FF),), ("ru", "uk", "bg", "sr", "mk", "be")),
    "greek": (((0x370, 0x3FF), (0x1F00, 0x1FFF)), ("el",)),
    "hebrew": (((0x590, 0x5FF),), ("he", "yi")),
    "arabic": (((0x600, 0x6FF), (0x750, 0x77F)), ("ar", "fa", "ur", "ckb")),
    "devanagari": (((0x900, 0x97F),), ("hi", "mr", "ne")),
    "bengali": (((0x980, 0x9FF),), ("bn",)),
    "gurmukhi": (((0xA00, 0xA7F),), ("pa",)),
    "gujarati": (((0xA80, 0xAFF),), ("gu",)),
    "tamil": (((0xB80, 0xBFF),), ("ta",)),
    "telugu": (((0xC00, 0xC7F),), ("te",)),
    "kannada": (((0xC80, 0xCFF),), ("kn",)),
    "malayalam": (((0xD00, 0xD7F),), ("ml",)),
    "thai": (((0xE00, 0xE7F),), ("th",)),
    "khmer": (((0x1780, 0x17FF),), ("km",)),
    "hangul": (((0xAC00, 0xD7AF), (0x1100, 0x11FF), (0x3130, 0x318F)),
               ("ko",)),
    "kana": (((0x3040, 0x309F), (0x30A0, 0x30FF)), ("ja",)),
    "han": (((0x4E00, 0x9FFF), (0x3400, 0x4DBF)), ()),   # zh-cn/zh-tw/ja
}

# distinctive Han characters: simplified-only vs traditional-only forms
# (characters shared by both orthographies carry no signal and are excluded
# symmetrically — compute the overlap FIRST so neither set keeps a shared
# character)
_HAN_SIMPLIFIED = set("这个们来说时国会学对发经点吗里后见长门问马语书车")
_HAN_TRADITIONAL = set("這個們來說時國會學對發經點嗎裡後見長門問馬語書車")
_HAN_SHARED = _HAN_SIMPLIFIED & _HAN_TRADITIONAL
_HAN_SIMPLIFIED -= _HAN_SHARED
_HAN_TRADITIONAL -= _HAN_SHARED


def detectable_languages() -> Tuple[str, ...]:
    """Codes detection is resourced for — the word-profile languages plus
    the script-sealed ones; mirrors the reference's Language enum
    (ISO 639-1/-3 + the zh-cn/zh-tw split)."""
    script_only = {"zh-cn", "zh-tw", "ja", "ko", "th", "km"}
    return tuple(sorted(script_only | set(_lang_profiles())))


# flat sorted (lo, hi, script) boundaries: one bisect per lookup instead of
# a linear scan over every script's ranges (texts pay this per character)
_SCRIPT_BOUNDS = sorted(
    (lo, hi, script)
    for script, (ranges, _) in _SCRIPTS.items() for lo, hi in ranges)
_SCRIPT_LOS = [b[0] for b in _SCRIPT_BOUNDS]


@functools.lru_cache(maxsize=8192)
def _script_of(ch: str) -> Optional[str]:
    cp = ord(ch)
    i = bisect.bisect_right(_SCRIPT_LOS, cp) - 1
    if i >= 0:
        lo, hi, script = _SCRIPT_BOUNDS[i]
        if cp <= hi:
            return script
    return None


def _profile_scores(tokens: List[str], candidates: Optional[Set[str]]
                    ) -> Dict[str, float]:
    scores: Dict[str, float] = {}
    for lang, profile in _lang_profiles().items():
        if candidates is not None and lang not in candidates:
            continue
        hits = sum(1 for t in tokens if t in profile)
        if hits:
            scores[lang] = hits / len(tokens)
    return scores


def detect_languages(s: str) -> Dict[str, float]:
    """Language → confidence, normalized to sum 1 over detected languages
    (≙ LangDetector.transformFn semantics: empty/no-signal → empty map).

    Covers the reference enum's breadth (LanguageDetector.scala:59): 67
    word-profile languages across Latin/Cyrillic/Arabic/Devanagari/Hebrew
    scripts plus script-sealed CJK, Thai, Khmer, Korean, Greek and Indic
    languages and the zh-cn/zh-tw split via character forms."""
    # letters by script
    script_counts: Dict[str, int] = {}
    han_simp = han_trad = 0
    for ch in s:
        if not ch.isalpha():
            continue
        sc = _script_of(ch)
        if sc is None:
            continue
        script_counts[sc] = script_counts.get(sc, 0) + 1
        if sc == "han":
            if ch in _HAN_SIMPLIFIED:
                han_simp += 1
            elif ch in _HAN_TRADITIONAL:
                han_trad += 1
    total = sum(script_counts.values())
    if not total:
        return {}

    scores: Dict[str, float] = {}
    # tokens per script family (space-separated scripts only)
    tokens_by_script: Dict[str, List[str]] = {}
    for t in _WORD_RE.findall(s):
        sc = _script_of(t[0])
        if sc is not None:
            tokens_by_script.setdefault(sc, []).append(t.lower())

    kana = script_counts.get("kana", 0)
    for script, cnt in script_counts.items():
        frac = cnt / total
        ranges, candidates = _SCRIPTS[script]
        if script == "han":
            # kana anywhere → the Han characters are Japanese kanji
            if kana:
                scores["ja"] = scores.get("ja", 0.0) + frac
            elif han_trad > han_simp:
                scores["zh-tw"] = scores.get("zh-tw", 0.0) + frac
            else:
                scores["zh-cn"] = scores.get("zh-cn", 0.0) + frac
            continue
        if script == "kana":
            scores["ja"] = scores.get("ja", 0.0) + frac
            continue
        if len(candidates) == 1:
            lang = candidates[0]
            scores[lang] = scores.get(lang, 0.0) + frac
            continue
        # script family resolved by word profiles (latin: open candidate set)
        toks = tokens_by_script.get(script, [])
        fam = _profile_scores(toks, set(candidates) or None) if toks else {}
        fam_total = sum(fam.values())
        if fam_total:
            for lang, sc_ in fam.items():
                scores[lang] = scores.get(lang, 0.0) + frac * sc_ / fam_total
        elif candidates:
            # no stop-word hit: fall back to the family's most common
            # language (ambiguous-script default, like Optimaize's priors)
            lang = candidates[0]
            scores[lang] = scores.get(lang, 0.0) + frac
        # latin with no hits contributes nothing (no-signal)

    total_score = sum(scores.values())
    if not total_score:
        return {}
    return {k: v / total_score for k, v in sorted(scores.items(),
                                                  key=lambda kv: -kv[1])}


class LangDetector(Transformer):
    """Text → RealMap of language confidences (≙ LangDetector.scala:46-61)."""

    in_kinds = (Text,)
    out_kind = RealMap
    is_device_op = False

    def transform(self, batch: ColumnBatch) -> Column:
        (f,) = self.input_features
        strings = _col_strings(batch[f.name])
        out = np.empty(len(strings), object)
        for i, s in enumerate(strings):
            out[i] = {} if s is None else detect_languages(s)
        return Column(RealMap, out)


# ---------------------------------------------------------------------------
# Name detection / NER (≙ HumanNameDetector.scala, NameEntityRecognizer.scala;
# OpenNLP models replaced by dictionaries + heuristics)
# ---------------------------------------------------------------------------

class _LazyMapping:
    """Dict/set-like view over a packaged resource, loaded on first use
    (≙ OpenNLPModels' lazily-loaded model cache).  Supports the read API of
    the dict/set constants it replaces: ``in``, iteration, ``len``, ``get``,
    ``[]``, ``keys``/``items``, and set union/intersection."""

    def __init__(self, loader):
        self._loader = loader
        self._data = None

    def _load(self):
        if self._data is None:
            self._data = self._loader()
        return self._data

    def __contains__(self, item):
        return item in self._load()

    def __iter__(self):
        return iter(self._load())

    def __len__(self):
        return len(self._load())

    def __getitem__(self, key):
        return self._load()[key]

    def get(self, key, default=None):
        d = self._load()
        return d.get(key, default) if hasattr(d, "get") else default

    def keys(self):
        d = self._load()
        return d.keys() if hasattr(d, "keys") else iter(d)

    def items(self):
        return self._load().items()

    def __or__(self, other):
        return set(self._load()) | set(other)

    def __and__(self, other):
        return set(self._load()) & set(other)


def _load_gender():
    from ..resources import gender_dictionary
    return gender_dictionary()


def _load_names():
    from ..resources import name_dictionary
    return name_dictionary()


# first-name → gender dictionary (≙ NameDetectUtils.DefaultGenderDictionary)
GENDER_DICT = _LazyMapping(_load_gender)

# surname + first-name union (≙ NameDetectUtils.DefaultNameDictionary)
NAME_DICT = _LazyMapping(_load_names)


def _name_tokens(s: Optional[str]) -> List[str]:
    """Lower-cased word tokens with salutations stripped (≙ NameDetectUtils
    preprocessing: honorifics like 'Dr.'/'Mrs.' never count as name hits)."""
    if not s:
        return []
    from ..resources import honorifics
    hon = honorifics()
    return [t.lower() for t in re.findall(r"[A-Za-z']+", s)
            if t.lower() not in hon]


class HumanNameDetectorModel(TransformerModel):
    out_kind = Text  # actual kind: NameStats (TextMap subtype)
    is_device_op = False

    def transform(self, batch: ColumnBatch) -> Column:
        from ..types import NameStats
        (f,) = self.input_features
        strings = _col_strings(batch[f.name])
        treat = self.fitted["treat_as_name"]
        out = np.empty(len(strings), object)
        for i, s in enumerate(strings):
            if not treat or s is None:
                out[i] = {}
                continue
            toks = _name_tokens(s)
            gender = "GenderNA"
            for t in toks:
                g = GENDER_DICT.get(t)
                if g:
                    gender = g
                    break
            out[i] = {"IsName": "true", "OriginalValue": s, "Gender": gender}
        return Column(NameStats, out)


class HumanNameDetector(Estimator):
    """Text → NameStats; fit decides whether the column is a name column by
    dictionary hit rate (≙ HumanNameDetector.scala:56-118: treatAsName from
    aggregated NameDetectStats, model emits IsName/OriginalValue/Gender)."""

    in_kinds = (Text,)
    out_kind = Text
    allow_label_as_input = False

    def __init__(self, name_threshold: float = 0.5, **params):
        super().__init__(name_threshold=name_threshold, **params)

    def fit(self, batch: ColumnBatch) -> TransformerModel:
        from ..types import NameStats
        (f,) = self.input_features
        strings = _col_strings(batch[f.name])
        hits = total = 0
        for s in strings:
            toks = _name_tokens(s)
            if not toks:
                continue
            total += 1
            if sum(1 for t in toks if t in NAME_DICT) / len(toks) >= 0.5:
                hits += 1
        frac = hits / total if total else 0.0
        treat = frac >= float(self.get("name_threshold", 0.5))
        model = HumanNameDetectorModel(
            fitted={"treat_as_name": bool(treat)}, **self.params)
        model.out_kind = NameStats
        model.metadata["treatAsName"] = bool(treat)
        model.metadata["predictedNameProb"] = frac
        return self._finalize_model(model)

    def out_kind_at(self, i: int):
        from ..types import NameStats
        return NameStats


class NameEntityRecognizer(Transformer):
    """Text → MultiPickListMap token → entity-tag sets (≙
    NameEntityRecognizer.scala:56-89; OpenNLP tagger replaced by a
    dictionary + capitalization heuristic tagging Person tokens)."""

    in_kinds = (Text,)
    out_kind = MultiPickListMap
    is_device_op = False

    def transform(self, batch: ColumnBatch) -> Column:
        (f,) = self.input_features
        strings = _col_strings(batch[f.name])
        out = np.empty(len(strings), object)
        for i, s in enumerate(strings):
            res: Dict[str, Set[str]] = {}
            if s:
                for tok in re.findall(r"[A-Za-z']+", s):
                    if tok[0].isupper() and tok.lower() in NAME_DICT:
                        res.setdefault(tok, set()).add("Person")
            out[i] = {k: frozenset(v) for k, v in res.items()}
        return Column(MultiPickListMap, out)


# ---------------------------------------------------------------------------
# LDA + Word2Vec — jitted XLA training loops (≙ OpLDA.scala wrapping Spark
# LDA, OpWord2Vec.scala wrapping Spark Word2Vec)
# ---------------------------------------------------------------------------

def _lda_em(counts: jnp.ndarray, k: int, iters: int, seed: int
            ) -> jnp.ndarray:
    """Full-batch multiplicative EM for topic-word probabilities on a dense
    doc-term count matrix.  One XLA program: `lax.fori_loop` over E/M matmul
    steps — the MXU does the work the reference delegates to Spark LDA."""
    n, v = counts.shape
    key = jax.random.PRNGKey(seed)
    topics = jax.random.uniform(key, (k, v), dtype=jnp.float32) + 0.1
    topics = topics / topics.sum(axis=1, keepdims=True)
    doc_topic = jnp.full((n, k), 1.0 / k, dtype=jnp.float32)

    def step(_, state):
        topics, doc_topic = state
        # E-step responsibilities via two matmuls; eps guards empty docs
        mix = doc_topic[:, :, None] * topics[None, :, :]          # [n,k,v]
        denom = mix.sum(axis=1, keepdims=True) + 1e-12
        resp = mix / denom                                        # [n,k,v]
        weighted = resp * counts[:, None, :]                      # [n,k,v]
        doc_topic = weighted.sum(axis=2) + 1e-3
        doc_topic = doc_topic / doc_topic.sum(axis=1, keepdims=True)
        topics = weighted.sum(axis=0) + 1e-3
        topics = topics / topics.sum(axis=1, keepdims=True)
        return topics, doc_topic

    topics, _ = jax.lax.fori_loop(0, iters, step, (topics, doc_topic))
    return topics


def _lda_infer(counts: jnp.ndarray, topics: jnp.ndarray, iters: int = 20
               ) -> jnp.ndarray:
    """Infer doc-topic mixtures for fixed topics (jitted E-step iterations)."""
    n = counts.shape[0]
    k = topics.shape[0]
    doc_topic = jnp.full((n, k), 1.0 / k, dtype=jnp.float32)

    def step(_, doc_topic):
        mix = doc_topic[:, :, None] * topics[None, :, :]
        denom = mix.sum(axis=1, keepdims=True) + 1e-12
        resp = (mix / denom * counts[:, None, :]).sum(axis=2) + 1e-3
        return resp / resp.sum(axis=1, keepdims=True)

    return jax.lax.fori_loop(0, iters, step, doc_topic)


class OpLDAModel(TransformerModel):
    out_kind = OPVector
    is_device_op = False

    def transform(self, batch: ColumnBatch) -> Column:
        (f,) = self.input_features
        col = batch[f.name]
        counts = jnp.maximum(
            jnp.asarray(np.asarray(col.values, np.float32)), 0.0)
        topics = jnp.asarray(self.fitted["topics"])
        mix = _lda_infer(counts, topics)
        return Column(OPVector, mix, meta=self.fitted["meta"])


class OpLDA(Estimator):
    """OPVector (term counts) → OPVector topic mixture (≙ OpLDA.scala:41;
    Spark LDA replaced by a jitted full-batch EM on device)."""

    in_kinds = (OPVector,)
    out_kind = OPVector

    def __init__(self, k: int = 10, max_iter: int = 20, seed: int = 42,
                 **params):
        super().__init__(k=k, max_iter=max_iter, seed=seed, **params)

    def fit(self, batch: ColumnBatch) -> TransformerModel:
        (f,) = self.input_features
        counts = jnp.maximum(
            jnp.asarray(np.asarray(batch[f.name].values, np.float32)), 0.0)
        k = int(self.get("k", 10))
        topics = _lda_em(counts, k, int(self.get("max_iter", 20)),
                         int(self.get("seed", 42)))
        cols = [VectorColumnMeta(f.name, f.kind.__name__,
                                 descriptor_value=f"topic_{i}")
                for i in range(k)]
        meta = VectorMeta(self.output_name(), cols)
        return self._finalize_model(OpLDAModel(
            fitted={"topics": np.asarray(topics), "meta": meta},
            **self.params))


def _w2v_train(centers: jnp.ndarray, contexts: jnp.ndarray,
               negatives: jnp.ndarray, vocab_size: int, dim: int,
               epochs: int, lr: float, seed: int) -> jnp.ndarray:
    """Skip-gram negative-sampling SGD, full-batch per epoch, jitted."""
    key = jax.random.PRNGKey(seed)
    k1, k2 = jax.random.split(key)
    emb = jax.random.normal(k1, (vocab_size, dim), jnp.float32) * 0.1
    ctx = jax.random.normal(k2, (vocab_size, dim), jnp.float32) * 0.1

    def loss_fn(params):
        emb, ctx = params
        ec = emb[centers]                       # [P, d]
        cc = ctx[contexts]                      # [P, d]
        nc = ctx[negatives]                     # [P, neg, d]
        pos = jax.nn.log_sigmoid(jnp.sum(ec * cc, axis=-1))
        neg = jax.nn.log_sigmoid(-jnp.einsum("pd,pnd->pn", ec, nc)).sum(-1)
        return -(pos + neg).mean()

    grad_fn = jax.grad(loss_fn)

    def step(_, params):
        g_emb, g_ctx = grad_fn(params)
        emb, ctx = params
        return emb - lr * g_emb, ctx - lr * g_ctx

    emb, _ = jax.lax.fori_loop(0, epochs, step, (emb, ctx))
    return emb


class OpWord2VecModel(TransformerModel):
    out_kind = OPVector
    is_device_op = False

    def transform(self, batch: ColumnBatch) -> Column:
        (f,) = self.input_features
        vocab: Dict[str, int] = {t: i for i, t in enumerate(self.fitted["vocab"])}
        emb = np.asarray(self.fitted["embeddings"])
        dim = emb.shape[1]
        out = np.zeros((len(batch), dim), np.float32)
        for i, toks in enumerate(batch[f.name].values):
            ids = [vocab[t] for t in (toks or []) if t in vocab]
            if ids:
                out[i] = emb[ids].mean(axis=0)
        return Column(OPVector, jnp.asarray(out), meta=self.fitted["meta"])


class OpWord2Vec(Estimator):
    """TextList → averaged word embedding (≙ OpWord2Vec.scala:41; Spark
    Word2Vec replaced by jitted skip-gram negative sampling; transform
    averages in-vocab token vectors, Spark-style)."""

    in_kinds = (TextList,)
    out_kind = OPVector

    def __init__(self, vector_size: int = 100, min_count: int = 5,
                 window: int = 5, num_negatives: int = 5, epochs: int = 50,
                 lr: float = 0.1, seed: int = 42, **params):
        super().__init__(vector_size=vector_size, min_count=min_count,
                         window=window, num_negatives=num_negatives,
                         epochs=epochs, lr=lr, seed=seed, **params)

    def fit(self, batch: ColumnBatch) -> TransformerModel:
        (f,) = self.input_features
        docs = [toks or [] for toks in batch[f.name].values]
        counts = Counter(t for d in docs for t in d)
        min_count = int(self.get("min_count", 5))
        vocab_list = sorted(t for t, c in counts.items() if c >= min_count)
        vocab = {t: i for i, t in enumerate(vocab_list)}
        dim = int(self.get("vector_size", 100))
        cols = [VectorColumnMeta(f.name, f.kind.__name__,
                                 descriptor_value=f"w2v_{i}")
                for i in range(dim)]
        meta = VectorMeta(self.output_name(), cols)
        if not vocab_list:
            model = OpWord2VecModel(
                fitted={"vocab": [], "meta": meta,
                        "embeddings": np.zeros((0, dim), np.float32)},
                **self.params)
            return self._finalize_model(model)
        window = int(self.get("window", 5))
        rng = np.random.default_rng(int(self.get("seed", 42)))
        centers, contexts = [], []
        for d in docs:
            ids = [vocab[t] for t in d if t in vocab]
            for i, c in enumerate(ids):
                for j in range(max(0, i - window), min(len(ids), i + window + 1)):
                    if j != i:
                        centers.append(c)
                        contexts.append(ids[j])
        if not centers:
            emb = np.zeros((len(vocab_list), dim), np.float32)
        else:
            n_neg = int(self.get("num_negatives", 5))
            negs = rng.integers(0, len(vocab_list),
                                size=(len(centers), n_neg))
            emb = np.asarray(_w2v_train(
                jnp.asarray(np.array(centers, np.int32)),
                jnp.asarray(np.array(contexts, np.int32)),
                jnp.asarray(negs.astype(np.int32)),
                len(vocab_list), dim, int(self.get("epochs", 50)),
                float(self.get("lr", 0.1)), int(self.get("seed", 42))))
        model = OpWord2VecModel(
            fitted={"vocab": vocab_list, "embeddings": emb, "meta": meta},
            **self.params)
        return self._finalize_model(model)
