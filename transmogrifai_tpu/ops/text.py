"""Text vectorization (reference: core/.../stages/impl/feature/
SmartTextVectorizer.scala:61, TextTokenizer.scala, OpHashingTF.scala,
OPCollectionHashingVectorizer.scala, TextLenTransformer.scala).

TPU design: tokenization + hashing happen host-side at transform time (strings
never reach the device); the hashed term-frequency matrix is the device-side
product.  Hashing uses a stable 32-bit FNV-1a (vectorizable, seed-stable across
processes — unlike Python's ``hash``).  The SmartTextVectorizer decision
(cardinality ≤ max → pivot one-hot, else hash) is made at fit time from a
single-pass TextStats reduction, so transform shapes are static for jit.
"""

from __future__ import annotations

import functools
import re
from collections import Counter
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..columns import Column, ColumnBatch
from ..stages.base import Estimator, Transformer, TransformerModel
from ..types import OPVector, Real, Text, TextList
from ..vector_meta import (NULL_INDICATOR, OTHER_INDICATOR, VectorColumnMeta,
                           VectorMeta)
from .categorical import _col_strings, top_values_by_count

_TOKEN_RE = re.compile(r"[A-Za-z0-9_']+")

def fnv1a_32(s: str) -> int:
    """Stable 32-bit FNV-1a string hash (host-side hashing-trick backbone)."""
    h = 2166136261
    for b in s.encode("utf-8"):
        h = ((h ^ b) * 16777619) & 0xFFFFFFFF
    return h


def tokenize_text(s: Optional[str], min_token_length: int = 1,
                  to_lowercase: bool = True) -> List[str]:
    """Simple language-agnostic tokenizer (≙ TextTokenizer with the default
    Lucene analyzer: lowercase + split on non-alphanumerics)."""
    if s is None:
        return []
    if to_lowercase:
        s = s.lower()
    return [t for t in _TOKEN_RE.findall(s) if len(t) >= min_token_length]


def hash_tokens_flat(token_lists: Sequence[Sequence[str]], num_hashes: int
                     ) -> Tuple[np.ndarray, np.ndarray]:
    """Tokens → (lens [N] int32, flat bucket ids [total] int32).

    Vectorized host prologue (SURVEY §7 hard part (b)): tokens flatten to one
    array, each DISTINCT token hashes once (np.unique + inverse codes)."""
    n = len(token_lists)
    lens = np.fromiter((len(t) for t in token_lists), np.int32, count=n)
    total = int(lens.sum())
    if not total:
        return lens, np.zeros(0, np.int32)
    flat = np.empty(total, dtype=object)
    pos = 0
    for toks in token_lists:
        flat[pos:pos + len(toks)] = toks
        pos += len(toks)
    # np.unique on the object array directly: astype(str) would allocate a
    # fixed-width U<longest-token> copy (one huge token → OOM)
    uniq, codes = np.unique(flat, return_inverse=True)
    buckets = np.fromiter((fnv1a_32(t) % num_hashes for t in uniq),
                          np.int64, count=len(uniq))
    return lens, buckets[codes].astype(np.int32)


def hash_tokens_to_counts(token_lists: Sequence[Sequence[str]], num_hashes: int,
                          binary: bool = False) -> np.ndarray:
    """Hashing trick: token lists → [N, num_hashes] term-frequency matrix
    (host path; counts land via one ``np.add.at`` scatter)."""
    lens, flat = hash_tokens_flat(token_lists, num_hashes)
    return _counts_from_flat(lens, flat, num_hashes, binary)


def _counts_from_flat(lens: np.ndarray, flat: np.ndarray, num_hashes: int,
                      binary: bool) -> np.ndarray:
    out = np.zeros((len(lens), num_hashes), dtype=np.float32)
    if not flat.size:
        return out
    rows = np.repeat(np.arange(len(lens)), lens)
    if binary:
        # dedupe (row, bucket) pairs on int64 keys and write the indicator
        # into the single output buffer — the old `(out > 0).astype(...)`
        # allocated a SECOND dense [N, H] copy just to threshold it, pure
        # waste whenever empty-token rows leave most of the matrix zero
        keys = np.unique(rows.astype(np.int64) * num_hashes + flat)
        out[keys // num_hashes, keys % num_hashes] = 1.0
        return out
    np.add.at(out, (rows, flat), 1.0)
    return out


def strings_to_hash_flat(strings: Sequence[Optional[str]], num_hashes: int
                         ) -> Tuple[np.ndarray, np.ndarray]:
    """Strings → (lens [N] int32, flat bucket ids [total] int32) in ONE
    native pass (tokenize + FNV + modulo, native/fasttok.cpp) — the host
    prologue of the hashing trick without per-token Python objects.  Rows the
    native tokenizer defers (non-ASCII content: unicode case folding must
    match Python's) are spliced back from the pure-Python path."""
    from ..native import load
    native = load("fasttok")
    if native is None:
        return hash_tokens_flat(
            [tokenize_text(s) for s in strings], num_hashes)
    lens, buckets, fallback = native.tokenize_hash(list(strings), num_hashes, 1)
    if not fallback:
        return lens, buckets
    fb_tok = {i: np.asarray([fnv1a_32(t) % num_hashes
                             for t in tokenize_text(strings[i])], np.int32)
              for i in fallback}
    out_lens = lens.copy()
    pieces: List[np.ndarray] = []
    pos = 0
    for i, L in enumerate(lens):
        if L < 0:
            out_lens[i] = len(fb_tok[i])
            pieces.append(fb_tok[i])
        elif L:
            pieces.append(buckets[pos:pos + L])
            pos += L
    flat = (np.concatenate(pieces).astype(np.int32) if pieces
            else np.zeros(0, np.int32))
    return out_lens, flat


@functools.partial(jax.jit, static_argnums=(2, 3, 4))
def _scatter_counts_device(ids, lens_padded, n, num_hashes, binary):
    """Flat bucket ids (+1 sentinel row/bin of padding) → [n, H] counts
    materialized in HBM — the hashed matrix never exists on the host, so the
    (slow) host link carries token ids instead of a dense [N, H] block."""
    rows = jnp.repeat(jnp.arange(n + 1), lens_padded,
                      total_repeat_length=ids.shape[0])
    counts = jnp.zeros((n + 1, num_hashes + 1), jnp.float32)
    counts = counts.at[rows, ids].add(1.0)
    counts = counts[:n, :num_hashes]
    return (counts > 0).astype(jnp.float32) if binary else counts


@functools.partial(jax.jit, static_argnums=(2, 3, 4))
def _scatter_counts_packed(words, lens_padded, n, num_hashes, binary):
    """Packed-wire variant: each int32 word carries THREE 10-bit bucket ids
    (token order preserved), tripling the effective host-link bandwidth of
    the hashing trick — the ids unpack with two shifts on device."""
    ids = jnp.stack([words & 0x3FF, (words >> 10) & 0x3FF,
                     (words >> 20) & 0x3FF], axis=1).reshape(-1)
    rows = jnp.repeat(jnp.arange(n + 1), lens_padded,
                      total_repeat_length=ids.shape[0])
    counts = jnp.zeros((n + 1, num_hashes + 1), jnp.float32)
    counts = counts.at[rows, ids].add(1.0)
    counts = counts[:n, :num_hashes]
    return (counts > 0).astype(jnp.float32) if binary else counts


def _size_class(n: int, floor: int = 1024) -> int:
    """Smallest {2^k, 1.5·2^k} >= n — tighter than pure powers of two (max
    33% padding instead of 100%) while keeping the jit-recompile count
    bounded at two shapes per octave."""
    if n <= floor:
        return floor
    k = int(np.ceil(np.log2(n)))
    for cap in ((1 << (k - 1)) + (1 << (k - 2)), 1 << k):
        if cap >= n:
            return cap
    return 1 << k


def _pack_ids3(flat: np.ndarray, num_hashes: int) -> np.ndarray:
    """Bucket ids (< 1024) → int32 words of three 10-bit lanes, padded with
    the sentinel bin ``num_hashes`` to a full final word."""
    total = int(flat.size)
    w = (total + 2) // 3
    ids = np.full(3 * w, num_hashes, dtype=np.int32)
    ids[:total] = flat
    return (ids[0::3] | (ids[1::3] << 10) | (ids[2::3] << 20)).astype(
        np.int32)


def hash_counts_on_device(token_lists: Sequence[Sequence[str]],
                          num_hashes: int, binary: bool = False,
                          dtype=None):
    """Device-resident hashing trick: ship (lens, flat bucket ids) — a few
    bytes per TOKEN — and scatter-add the [N, H] count matrix in HBM.  The
    wire cost drops ~H/avg_tokens-fold vs shipping the dense counts (at 1M
    rows x 512 bins that is 6 GB → ~25 MB on the tunneled link).  Flat
    length pads to the next power of two so jit recompiles stay bounded.
    ``dtype`` (e.g. bf16 at scale — counts ≤ 256 are exact) sets storage."""
    lens, flat = hash_tokens_flat(token_lists, num_hashes)
    return device_counts_from_flat(lens, flat, num_hashes, binary, dtype)


def device_counts_from_flat(lens: np.ndarray, flat: np.ndarray,
                            num_hashes: int, binary: bool = False,
                            dtype=None, device_ids=None):
    n = len(lens)
    total = int(flat.size)
    if num_hashes < 1024:
        # packed wire: 3 ids per int32 word (sentinel bin fits 10 bits)
        if device_ids is None:
            words = _pack_ids3(flat, num_hashes)
            cap = _size_class(words.size)
            words_p = np.full(cap, _sentinel3(num_hashes), dtype=np.int32)
            words_p[:words.size] = words
            device_ids = jnp.asarray(words_p)
        cap = int(device_ids.shape[0])
        lens_p = np.append(lens, np.int32(3 * cap - total)).astype(np.int32)
        out = _scatter_counts_packed(device_ids, jnp.asarray(lens_p),
                                     n, num_hashes, bool(binary))
    else:
        cap = 1 << max(10, int(np.ceil(np.log2(max(total, 1)))))
        ids_p = np.full(cap, num_hashes, dtype=np.int32)     # sentinel bin
        ids_p[:total] = flat
        lens_p = np.append(lens, np.int32(cap - total)).astype(np.int32)
        out = _scatter_counts_device(jnp.asarray(ids_p), jnp.asarray(lens_p),
                                     n, num_hashes, bool(binary))
    return out if dtype is None or out.dtype == dtype else out.astype(dtype)


def _sentinel3(num_hashes: int) -> np.int32:
    """An int32 word whose three 10-bit lanes all hold the sentinel bin."""
    return np.int32(num_hashes | (num_hashes << 10) | (num_hashes << 20))


# device assembly kicks in when the dense block would exceed this many
# elements (16 MB of f32) — below it, host numpy + one bf16-wire transfer
# in the combiner is cheaper than per-block dispatch latency
_DEVICE_ASSEMBLE_ELEMS = 1 << 22

# hash spaces at/above this width vectorize SPARSE by default (the dense
# [N, num_hashes] block at 4096+ columns starts to dominate memory while
# its density collapses); override per stage with sparse_hashing=True/False
SPARSE_MIN_HASHES = 4096


def _one_hot_on_device(ids: np.ndarray, width: int, dtype=jnp.float32):
    # narrowest wire dtype — the host link, not the expand, is the cost
    wire = ids.astype(np.uint8) if width < 256 else ids.astype(np.int32)
    idsd = jnp.asarray(wire).astype(jnp.int32)
    return (idsd[:, None] == jnp.arange(width)[None, :]).astype(dtype)


class TextTokenizer(Transformer):
    """Text → TextList of tokens (≙ TextTokenizer.scala)."""

    in_kinds = (Text,)
    out_kind = TextList
    is_device_op = False

    def __init__(self, min_token_length: int = 1, to_lowercase: bool = True, **params):
        super().__init__(min_token_length=min_token_length,
                         to_lowercase=to_lowercase, **params)

    def transform(self, batch: ColumnBatch) -> Column:
        (f,) = self.input_features
        strings = _col_strings(batch[f.name])
        toks = np.empty(len(strings), dtype=object)
        for i, s in enumerate(strings):
            toks[i] = tokenize_text(s, self.get("min_token_length", 1),
                                    self.get("to_lowercase", True))
        return Column(TextList, toks)


class TextLenTransformer(Transformer):
    """Text length feature (≙ TextLenTransformer.scala)."""

    out_kind = Real
    is_device_op = False

    def transform(self, batch: ColumnBatch) -> Column:
        (f,) = self.input_features
        strings = _col_strings(batch[f.name])
        vals = np.array([0.0 if s is None else float(len(s)) for s in strings],
                        np.float32)
        mask = np.array([s is not None for s in strings])
        return Column(Real, vals, mask=mask)


class HashingVectorizerModel(TransformerModel):
    out_kind = OPVector
    is_device_op = False

    def transform(self, batch: ColumnBatch) -> Column:
        from ..columns import feature_matrix_dtype

        num_hashes = self.get("num_hashes")
        binary = self.get("binary", False)
        n = len(batch)
        # output width: shared hash space folds every feature into ONE block
        width = (num_hashes if self.get("shared_hash_space", False)
                 else num_hashes * len(self.input_features))
        n_elems = n * width
        on_device = n_elems >= _DEVICE_ASSEMBLE_ELEMS
        dtype = feature_matrix_dtype(n_elems)
        blocks = []
        for f in self.input_features:
            col = batch[f.name]
            if col.is_host_object() and len(col.values) and isinstance(
                    next((v for v in col.values if v is not None), ""), list):
                lens, flat = hash_tokens_flat(
                    [v or [] for v in col.values], num_hashes)
            else:
                from .text_profile import column_profile
                prof = column_profile(col)
                lens, flat = prof.buckets(num_hashes)
                if on_device:
                    blocks.append(device_counts_from_flat(
                        lens, flat, num_hashes, binary=binary, dtype=dtype,
                        device_ids=prof.device_ids(num_hashes)))
                    continue
            blocks.append(
                device_counts_from_flat(lens, flat, num_hashes,
                                        binary=binary, dtype=dtype)
                if on_device else
                _counts_from_flat(lens, flat, num_hashes, binary))
        if on_device:
            arr = (sum(blocks) if self.get("shared_hash_space", False)
                   else jnp.concatenate(blocks, axis=1))
            return Column(OPVector, arr, meta=self.fitted["meta"])
        if self.get("shared_hash_space", False):
            arr = np.sum(blocks, axis=0)
        else:
            arr = np.concatenate(blocks, axis=1)
        return Column(OPVector, jnp.asarray(arr), meta=self.fitted["meta"])


class HashingVectorizer(Estimator):
    """Token/text hashing vectorizer (≙ OpHashingTF +
    OPCollectionHashingVectorizer): each feature hashed into its own (or a
    shared) ``num_hashes``-wide space."""

    out_kind = OPVector

    def __init__(self, num_hashes: int = 512, binary: bool = False,
                 shared_hash_space: bool = False, **params):
        super().__init__(num_hashes=num_hashes, binary=binary,
                         shared_hash_space=shared_hash_space, **params)

    def fit(self, batch: ColumnBatch) -> TransformerModel:
        cols_meta = []
        n_blocks = 1 if self.get("shared_hash_space") else len(self.input_features)
        feats = (self.input_features[:1] if self.get("shared_hash_space")
                 else self.input_features)
        for f in feats:
            for j in range(self.get("num_hashes")):
                cols_meta.append(VectorColumnMeta(
                    f.name, f.kind.__name__, descriptor_value=f"hash_{j}"))
        meta = VectorMeta(self.output_name(), cols_meta)
        return self._finalize_model(HashingVectorizerModel(
            fitted={"meta": meta}, **self.params))


class TextStats:
    """Single-pass text cardinality statistics monoid
    (≙ SmartTextVectorizer.TextStats, SmartTextVectorizer.scala:182-230)."""

    def __init__(self, value_counts: Optional[Counter] = None,
                 length_counts: Optional[Counter] = None):
        self.value_counts = value_counts or Counter()
        self.length_counts = length_counts or Counter()

    @property
    def cardinality(self) -> int:
        return len(self.value_counts)

    @property
    def length_std_dev(self) -> float:
        """Standard deviation of the FULL (cleaned) value lengths — exactly
        the reference's TextStats.lengthStdDev (SmartTextVectorizer.scala:
        126 builds lengthCounts from text.length, :190-193 the stddev);
        drives the ID-like Ignore branch."""
        n = sum(self.length_counts.values())
        if n == 0:
            return 0.0
        mean = sum(l * c for l, c in self.length_counts.items()) / n
        var = sum(c * (l - mean) ** 2 for l, c in self.length_counts.items()) / n
        return var ** 0.5

    def combine(self, other: "TextStats") -> "TextStats":
        return TextStats(self.value_counts + other.value_counts,
                         self.length_counts + other.length_counts)

    @staticmethod
    def of_column(strings: np.ndarray, max_card: int) -> "TextStats":
        vc, lc = Counter(), Counter()
        for s in strings:
            if s is None:
                continue
            if len(vc) <= max_card:
                vc[s] += 1
            lc[len(s)] += 1
        return TextStats(vc, lc)


class SmartTextVectorizerModel(TransformerModel):
    out_kind = OPVector
    is_device_op = False
    supports_staging = True

    def transform_staged(self, batch: ColumnBatch):
        """Host prologue: cached column profiles → compact wire (packed
        token words, per-row lens, vocab codes, null bits).  Device body:
        scatter-add hash counts + one-hot pivots + null indicators, concat —
        traceable, so the whole block fuses into the surrounding program."""
        from ..columns import (feature_matrix_dtype, pack_bits,
                               unpack_bits_device)
        from .categorical import encode_column
        from .text_profile import column_profile

        if self.fitted.get("sparse"):
            return None          # sparse representation assembles host-side
        num_hashes = self.get("num_hashes")
        if num_hashes >= 1024:
            return None          # packed 10-bit wire only
        n = len(batch)
        strategies = self.fitted["strategies"]
        track_nulls = self.get("track_nulls", True)
        est_width = sum(
            num_hashes if strategies[f.name] == "hash" else 32
            for f in self.input_features)
        dtype = feature_matrix_dtype(n * est_width)
        wire: Dict[str, Any] = {}
        plan: List[Tuple[str, Any, Tuple[Optional[str], ...]]] = []
        for i, f in enumerate(self.input_features):
            col = batch[f.name]
            if not col.is_host_object():
                return None      # exotic residency: eager path
            strat = strategies[f.name]
            prof = column_profile(col)
            if strat == "pivot":
                vocab = self.fitted["vocabs"][f.name]
                other = len(vocab)
                ids = encode_column(col, vocab, other)
                wire[f"ids{i}"] = (ids.astype(np.uint8) if other + 1 < 256
                                   else ids)
                plan.append(("pivot", other + 2, (f"ids{i}",)))
            elif strat == "ignore":
                if track_nulls:
                    wire[f"null{i}"] = pack_bits(prof.null)
                    plan.append(("null", None, (f"null{i}",)))
            else:
                words = prof.device_ids(num_hashes)
                total = int(prof.tok_hash.size)
                cap = int(words.shape[0])
                wire[f"words{i}"] = words
                wire[f"lens{i}"] = np.append(
                    prof.tok_lens, np.int32(3 * cap - total)).astype(np.int32)
                nk = None
                if track_nulls:
                    nk = f"null{i}"
                    wire[nk] = pack_bits(prof.null)
                plan.append(("hash", num_hashes, (f"words{i}", f"lens{i}", nk)))
        meta = self.fitted["meta"]

        def body(w):
            blocks = []
            for kind, info, keys in plan:
                if kind == "pivot":
                    ids = jnp.asarray(w[keys[0]]).astype(jnp.int32)
                    blocks.append((ids[:, None] == jnp.arange(info)[None, :]
                                   ).astype(dtype))
                elif kind == "null":
                    blocks.append(unpack_bits_device(
                        w[keys[0]], n)[:, None].astype(dtype))
                else:
                    words, lens_p = w[keys[0]], w[keys[1]]
                    h = info
                    ids = jnp.stack([words & 0x3FF, (words >> 10) & 0x3FF,
                                     (words >> 20) & 0x3FF], axis=1).reshape(-1)
                    nr = lens_p.shape[0] - 1
                    rows = jnp.repeat(jnp.arange(nr + 1), lens_p,
                                      total_repeat_length=ids.shape[0])
                    counts = jnp.zeros((nr + 1, h + 1), jnp.float32)
                    counts = counts.at[rows, ids].add(1.0)[:nr, :h].astype(dtype)
                    if keys[2] is not None:
                        counts = jnp.concatenate(
                            [counts,
                             unpack_bits_device(w[keys[2]], nr)[:, None]
                             .astype(dtype)],
                            axis=1)
                    blocks.append(counts)
            if not blocks:
                return Column(OPVector, jnp.zeros((n, 0), jnp.float32),
                              meta=meta)
            return Column(OPVector, jnp.concatenate(blocks, axis=1), meta=meta)

        return wire, body

    def _transform_sparse(self, batch: ColumnBatch) -> Column:
        """Fused hashed-text -> device SparseMatrix: the flat bucket stream
        dedupes host-side and ships as COO entries — the dense
        [N, num_hashes] matrix is NEVER materialized, so peak memory scales
        with nnz instead of rows x num_hashes.  Pivot/null blocks ride along
        as (tiny) dense blocks folded into the same entry stream."""
        from ..sparse.transform import combine_blocks, sparse_from_hash_flat
        from .categorical import encode_column
        from .text_profile import column_profile

        num_hashes = self.get("num_hashes")
        n = len(batch)
        strategies = self.fitted["strategies"]
        track_nulls = self.get("track_nulls", True)
        blocks: List[Any] = []
        for f in self.input_features:
            strat = strategies[f.name]
            prof = column_profile(batch[f.name])
            if strat == "pivot":
                vocab = self.fitted["vocabs"][f.name]
                other = len(vocab)
                ids = encode_column(batch[f.name], vocab, other)
                width = other + 2  # OTHER + null
                blocks.append(np.asarray(
                    ids[:, None] == np.arange(width)[None, :], np.float32))
            elif strat == "ignore":
                if track_nulls:
                    blocks.append(prof.null.astype(np.float32)[:, None])
            else:  # hash
                lens, flat = prof.buckets(num_hashes)
                blocks.append(sparse_from_hash_flat(
                    lens, flat, num_hashes, record=False))
                if track_nulls:
                    blocks.append(prof.null.astype(np.float32)[:, None])
        sm = combine_blocks(blocks, n)
        return Column(OPVector, sm, meta=self.fitted["meta"])

    def transform(self, batch: ColumnBatch) -> Column:
        from ..columns import feature_matrix_dtype
        from .text_profile import column_profile

        if self.fitted.get("sparse"):
            return self._transform_sparse(batch)
        num_hashes = self.get("num_hashes")
        n = len(batch)
        strategies = self.fitted["strategies"]
        est_width = sum(
            num_hashes if strategies[f.name] == "hash" else 32
            for f in self.input_features)
        on_device = n * est_width >= _DEVICE_ASSEMBLE_ELEMS
        dtype = feature_matrix_dtype(n * est_width)
        blocks = []
        for f in self.input_features:
            strat = strategies[f.name]
            prof = column_profile(batch[f.name])
            if strat == "pivot":
                from .categorical import encode_column
                vocab = self.fitted["vocabs"][f.name]
                other = len(vocab)
                ids = encode_column(batch[f.name], vocab, other)
                width = other + 2  # OTHER + null
                blocks.append(
                    _one_hot_on_device(ids, width, dtype) if on_device else
                    np.asarray(ids[:, None] == np.arange(width)[None, :],
                               np.float32))
            elif strat == "ignore":
                if self.get("track_nulls", True):
                    blocks.append(
                        jnp.asarray(prof.null)[:, None].astype(dtype)
                        if on_device else
                        prof.null.astype(np.float32)[:, None])
            else:  # hash
                lens, flat = prof.buckets(num_hashes)
                if on_device:
                    h = device_counts_from_flat(
                        lens, flat, num_hashes, dtype=dtype,
                        device_ids=prof.device_ids(num_hashes))
                    if self.get("track_nulls", True):
                        h = jnp.concatenate(
                            [h, jnp.asarray(prof.null)[:, None].astype(dtype)],
                            axis=1)
                else:
                    h = _counts_from_flat(lens, flat, num_hashes, False)
                    if self.get("track_nulls", True):
                        h = np.concatenate(
                            [h, prof.null.astype(np.float32)[:, None]], axis=1)
                blocks.append(h)
        if on_device and blocks:
            return Column(OPVector, jnp.concatenate(blocks, axis=1),
                          meta=self.fitted["meta"])
        arr = (np.concatenate(blocks, axis=1) if blocks
               else np.zeros((len(batch), 0), np.float32))
        return Column(OPVector, jnp.asarray(arr), meta=self.fitted["meta"])


class SmartTextVectorizer(Estimator):
    """Cardinality-adaptive text vectorization (≙ SmartTextVectorizer.scala:61):
    one TextStats pass; per feature, cardinality ≤ max_cardinality → pivot
    one-hot (like categorical); else value-length stddev below
    ``min_length_std_dev`` (ID-like; branch off by default) → ignore; else
    tokenize+hash."""

    out_kind = OPVector

    def __init__(self, max_cardinality: int = 30, top_k: int = 20,
                 min_support: int = 10, num_hashes: int = 512,
                 track_nulls: bool = True, auto_detect_languages: bool = False,
                 min_length_std_dev: float = 0.0,
                 sparse_hashing: Any = "auto", **params):
        # sparse_hashing: "auto" -> sparse when num_hashes >= SPARSE_MIN_HASHES
        # and any feature hashes; True/False force/forbid the sparse output
        super().__init__(max_cardinality=max_cardinality, top_k=top_k,
                         min_support=min_support, num_hashes=num_hashes,
                         track_nulls=track_nulls,
                         auto_detect_languages=auto_detect_languages,
                         min_length_std_dev=min_length_std_dev,
                         sparse_hashing=sparse_hashing, **params)

    def fit(self, batch: ColumnBatch) -> TransformerModel:
        from collections import Counter

        from .text_profile import column_profile

        strategies: Dict[str, str] = {}
        vocabs: Dict[str, Dict[str, int]] = {}
        cols_meta: List[VectorColumnMeta] = []
        max_card = self.get("max_cardinality")
        for f in self.input_features:
            # ONE cached native pass serves the TextStats fit reduction, the
            # transform's tokenize+hash, and RawFeatureFilter's stats
            prof = column_profile(batch[f.name])
            iv = prof.values(max_card)
            stats = TextStats(Counter(iv.value_counts()),
                              Counter(prof.length_counts()))
            if stats.cardinality <= max_card:
                # card <= maxCardinality -> pivot (the reference pivots even
                # single-value columns; SmartTextVectorizer.scala:92-96)
                strategies[f.name] = "pivot"
                top = top_values_by_count(stats.value_counts,
                                          self.get("top_k"),
                                          self.get("min_support"))
                vocab = {v: i for i, v in enumerate(top)}
                vocabs[f.name] = vocab
                for v in top:
                    cols_meta.append(VectorColumnMeta(
                        f.name, f.kind.__name__, indicator_value=v))
                cols_meta.append(VectorColumnMeta(
                    f.name, f.kind.__name__, indicator_value=OTHER_INDICATOR))
                cols_meta.append(VectorColumnMeta(
                    f.name, f.kind.__name__, indicator_value=NULL_INDICATOR))
            elif stats.length_std_dev < self.get("min_length_std_dev", 0.0):
                # ID-like: high cardinality with near-constant token length
                # (SmartTextVectorizer.scala:94 Ignore branch; off by default
                # like the reference's MinTextLengthStdDev = 0)
                strategies[f.name] = "ignore"
                if self.get("track_nulls", True):
                    cols_meta.append(VectorColumnMeta(
                        f.name, f.kind.__name__, indicator_value=NULL_INDICATOR))
            else:
                strategies[f.name] = "hash"
                for j in range(self.get("num_hashes")):
                    cols_meta.append(VectorColumnMeta(
                        f.name, f.kind.__name__, descriptor_value=f"hash_{j}"))
                if self.get("track_nulls", True):
                    cols_meta.append(VectorColumnMeta(
                        f.name, f.kind.__name__, indicator_value=NULL_INDICATOR))
        meta = VectorMeta(self.output_name(), cols_meta)
        mode = self.get("sparse_hashing", "auto")
        use_sparse = (any(s == "hash" for s in strategies.values())
                      and (mode is True
                           or (mode == "auto" and self.get("num_hashes")
                               >= SPARSE_MIN_HASHES)))
        model = SmartTextVectorizerModel(
            fitted={"strategies": strategies, "vocabs": vocabs, "meta": meta,
                    "sparse": use_sparse},
            **self.params)
        model.metadata["strategies"] = dict(strategies)
        model.metadata["sparse"] = use_sparse
        return self._finalize_model(model)


class TextListVectorizer(HashingVectorizer):
    """TextList → hashed vector (tokens already split)."""
