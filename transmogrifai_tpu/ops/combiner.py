"""VectorsCombiner — assemble feature vectors and merge their lineage metadata
(reference: core/.../stages/impl/feature/VectorsCombiner.scala).

A pure concat on device; metadata flattening mirrors OpVectorMetadata.flatten.
"""

from __future__ import annotations

from typing import List

import jax.numpy as jnp

from ..columns import Column, ColumnBatch, to_device_f32
from ..stages.base import Transformer
from ..types import OPVector
from ..vector_meta import VectorColumnMeta, VectorMeta


class VectorsCombiner(Transformer):
    in_kinds = None
    out_kind = OPVector

    def output_name(self) -> str:
        return f"features_{self.uid[-6:]}"

    def transform(self, batch: ColumnBatch) -> Column:
        from ..columns import feature_matrix_dtype
        from ..sparse.matrix import SparseMatrix

        import jax
        import numpy as np

        cols = [batch[f.name] for f in self.input_features]
        if any(isinstance(c.values, SparseMatrix) for c in cols):
            return self._transform_sparse(batch, cols)

        arrays, metas = [], []
        width = 0
        for f in self.input_features:
            col = batch[f.name]
            v = col.values
            if not (isinstance(v, jax.Array)
                    and v.dtype in (jnp.float32, jnp.bfloat16)):
                v = to_device_f32(v)
            if v.ndim == 1:
                v = v[:, None]
            width += v.shape[1]
            arrays.append(v)
            if col.meta is not None:
                metas.append(col.meta)
            else:
                metas.append(VectorMeta(f.name, [
                    VectorColumnMeta(f.name, f.kind.__name__)
                    for _ in range(v.shape[1])]))
        meta = VectorMeta.flatten(self.output_name(), metas)
        n = len(batch)
        dtype = feature_matrix_dtype(n * width)
        arrays = [a if a.dtype == dtype else a.astype(dtype) for a in arrays]
        return Column(OPVector, jnp.concatenate(arrays, axis=1), meta=meta)

    def _transform_sparse(self, batch: ColumnBatch, cols) -> Column:
        """Any sparse input block makes the combined matrix sparse: dense
        sibling blocks contribute their nonzero cells to the shared COO
        stream at the same column offsets the dense concat would use, so
        the lineage metadata stays layout-identical."""
        import numpy as np

        from ..sparse.matrix import SparseMatrix
        from ..sparse.transform import combine_blocks

        blocks, metas = [], []
        for f, col in zip(self.input_features, cols):
            v = col.values
            if not isinstance(v, SparseMatrix):
                v = np.asarray(v, dtype=np.float32)
                if v.ndim == 1:
                    v = v[:, None]
            w = v.shape[1]
            blocks.append(v)
            if col.meta is not None:
                metas.append(col.meta)
            else:
                metas.append(VectorMeta(f.name, [
                    VectorColumnMeta(f.name, f.kind.__name__)
                    for _ in range(w)]))
        meta = VectorMeta.flatten(self.output_name(), metas)
        return Column(OPVector, combine_blocks(blocks, len(batch)), meta=meta)
