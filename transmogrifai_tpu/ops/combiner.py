"""VectorsCombiner — assemble feature vectors and merge their lineage metadata
(reference: core/.../stages/impl/feature/VectorsCombiner.scala).

A pure concat on device; metadata flattening mirrors OpVectorMetadata.flatten.
"""

from __future__ import annotations

from typing import List

import jax.numpy as jnp

from ..columns import Column, ColumnBatch, to_device_f32
from ..stages.base import Transformer
from ..types import OPVector
from ..vector_meta import VectorColumnMeta, VectorMeta


class VectorsCombiner(Transformer):
    in_kinds = None
    out_kind = OPVector

    def output_name(self) -> str:
        return f"features_{self.uid[-6:]}"

    def transform(self, batch: ColumnBatch) -> Column:
        from ..columns import feature_matrix_dtype

        import jax

        arrays, metas = [], []
        width = 0
        for f in self.input_features:
            col = batch[f.name]
            v = col.values
            if not (isinstance(v, jax.Array)
                    and v.dtype in (jnp.float32, jnp.bfloat16)):
                v = to_device_f32(v)
            if v.ndim == 1:
                v = v[:, None]
            width += v.shape[1]
            arrays.append(v)
            if col.meta is not None:
                metas.append(col.meta)
            else:
                metas.append(VectorMeta(f.name, [
                    VectorColumnMeta(f.name, f.kind.__name__)
                    for _ in range(v.shape[1])]))
        meta = VectorMeta.flatten(self.output_name(), metas)
        n = len(batch)
        dtype = feature_matrix_dtype(n * width)
        arrays = [a if a.dtype == dtype else a.astype(dtype) for a in arrays]
        return Column(OPVector, jnp.concatenate(arrays, axis=1), meta=meta)
