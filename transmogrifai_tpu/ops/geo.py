"""Geolocation vectorizer (reference: core/.../stages/impl/feature/
GeolocationVectorizer.scala): fill missing (lat, lon, accuracy) with the
train mean and track nulls.
"""

from __future__ import annotations

from typing import List

import jax.numpy as jnp
import numpy as np

from ..columns import Column, ColumnBatch
from ..stages.base import Estimator, TransformerModel
from ..types import OPVector
from ..vector_meta import NULL_INDICATOR, VectorColumnMeta, VectorMeta


def _geo_arrays(col) -> tuple:
    """Column of Geolocation → ([N,3] float32, [N] bool mask)."""
    if col.is_host_object():
        n = len(col.values)
        arr = np.zeros((n, 3), np.float32)
        mask = np.zeros(n, bool)
        for i, v in enumerate(col.values):
            if v:
                arr[i] = v[:3]
                mask[i] = True
        return arr, mask
    arr = np.asarray(col.values, np.float32)
    mask = (np.ones(len(arr), bool) if col.mask is None else np.asarray(col.mask))
    return arr, mask


class GeolocationVectorizerModel(TransformerModel):
    out_kind = OPVector
    is_device_op = False

    def transform(self, batch: ColumnBatch) -> Column:
        outs = []
        for k, f in enumerate(self.input_features):
            arr, mask = _geo_arrays(batch[f.name])
            fill = np.asarray(self.fitted["fills"][k])
            filled = np.where(mask[:, None], arr, fill[None, :])
            outs.append(filled)
            if self.get("track_nulls", True):
                outs.append((~mask).astype(np.float32)[:, None])
        out = np.concatenate(outs, axis=1)
        return Column(OPVector, jnp.asarray(out), meta=self.fitted["meta"])


class GeolocationVectorizer(Estimator):
    out_kind = OPVector

    def __init__(self, track_nulls: bool = True, fill_mode: str = "mean", **params):
        super().__init__(track_nulls=track_nulls, fill_mode=fill_mode, **params)

    def fit(self, batch: ColumnBatch) -> TransformerModel:
        fills, cols_meta = [], []
        for f in self.input_features:
            arr, mask = _geo_arrays(batch[f.name])
            if self.get("fill_mode") == "mean" and mask.any():
                fill = arr[mask].mean(axis=0)
            else:
                fill = np.zeros(3, np.float32)
            fills.append(fill)
            for d in ("lat", "lon", "accuracy"):
                cols_meta.append(VectorColumnMeta(
                    f.name, f.kind.__name__, descriptor_value=d))
            if self.get("track_nulls", True):
                cols_meta.append(VectorColumnMeta(
                    f.name, f.kind.__name__, indicator_value=NULL_INDICATOR))
        meta = VectorMeta(self.output_name(), cols_meta)
        return self._finalize_model(GeolocationVectorizerModel(
            fitted={"fills": np.stack(fills), "meta": meta}, **self.params))
