"""Temporal vectorizers (reference: core/.../stages/impl/feature/
DateToUnitCircleTransformer.scala, DateListVectorizer.scala,
TimePeriodTransformer.scala).

Dates are epoch-milliseconds (Integral storage).  Unit-circle embedding —
sin/cos of the requested periods — is a pure device op; the period extraction
(hour-of-day etc.) is modular arithmetic on ms, jit-friendly.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from ..columns import Column, ColumnBatch
from ..stages.base import Estimator, Transformer, TransformerModel
from ..types import Date, DateList, Integral, OPVector, Real
from ..vector_meta import NULL_INDICATOR, VectorColumnMeta, VectorMeta

_MS_HOUR = 3600 * 1000
_MS_DAY = 24 * _MS_HOUR
_MS_WEEK = 7 * _MS_DAY
# epoch 1970-01-01 was a Thursday; shift so 0 = Monday like ISO
_EPOCH_DOW_SHIFT = 3 * _MS_DAY
_MS_YEAR = int(365.2425 * _MS_DAY)


def _period_fraction(ms: np.ndarray, period: str) -> np.ndarray:
    """Fraction in [0, 1) of the given circular period.

    The modulo runs on host in int64: epoch-milliseconds (~1.5e12) overflow
    int32 and lose ~131 s of resolution in float32, so only the small
    remainder is converted to float32 for the device sin/cos."""
    ms = np.asarray(ms, np.int64)
    if period == "HourOfDay":
        shift, per = 0, _MS_DAY
    elif period == "DayOfWeek":
        shift, per = _EPOCH_DOW_SHIFT, _MS_WEEK
    elif period == "DayOfMonth":
        # approximate month as 30.44 days (exact calendar month needs host calc)
        shift, per = 0, int(30.44 * _MS_DAY)
    elif period == "DayOfYear":
        shift, per = 0, _MS_YEAR
    else:
        raise ValueError(f"unknown time period {period}")
    return (((ms + shift) % per) / per).astype(np.float32)


class DateToUnitCircleModel(TransformerModel):
    out_kind = OPVector
    is_device_op = False  # int64 host modulo pre-pass, then device sin/cos

    def transform(self, batch: ColumnBatch) -> Column:
        periods = self.get("periods")
        outs = []
        for f in self.input_features:
            col = batch[f.name]
            v = np.asarray(col.values, np.int64)
            m = (jnp.ones(v.shape[0], bool) if col.mask is None
                 else jnp.asarray(col.mask))
            for p in periods:
                frac = jnp.asarray(_period_fraction(v, p))
                ang = 2 * jnp.pi * frac
                outs.append(jnp.where(m, jnp.sin(ang), 0.0).astype(jnp.float32)[:, None])
                outs.append(jnp.where(m, jnp.cos(ang), 0.0).astype(jnp.float32)[:, None])
            if self.get("track_nulls", True):
                outs.append((~m).astype(jnp.float32)[:, None])
        return Column(OPVector, jnp.concatenate(outs, axis=1), meta=self.fitted["meta"])


class DateToUnitCircleVectorizer(Estimator):
    """sin/cos circular embedding of date periods
    (≙ DateToUnitCircleTransformer + transmogrify's circular-date default)."""

    out_kind = OPVector

    def __init__(self, periods: Sequence[str] = ("HourOfDay", "DayOfWeek",
                                                 "DayOfMonth", "DayOfYear"),
                 track_nulls: bool = True, **params):
        super().__init__(periods=list(periods), track_nulls=track_nulls, **params)

    def fit(self, batch: ColumnBatch) -> TransformerModel:
        cols_meta: List[VectorColumnMeta] = []
        for f in self.input_features:
            for p in self.get("periods"):
                cols_meta.append(VectorColumnMeta(
                    f.name, f.kind.__name__, descriptor_value=f"sin({p})"))
                cols_meta.append(VectorColumnMeta(
                    f.name, f.kind.__name__, descriptor_value=f"cos({p})"))
            if self.get("track_nulls", True):
                cols_meta.append(VectorColumnMeta(
                    f.name, f.kind.__name__, indicator_value=NULL_INDICATOR))
        meta = VectorMeta(self.output_name(), cols_meta)
        return self._finalize_model(DateToUnitCircleModel(
            fitted={"meta": meta}, **self.params))


class TimePeriodTransformer(Transformer):
    """Date → integral period value (≙ TimePeriodTransformer.scala)."""

    out_kind = Integral

    def __init__(self, period: str = "DayOfWeek", **params):
        super().__init__(period=period, **params)

    def transform(self, batch: ColumnBatch) -> Column:
        (f,) = self.input_features
        col = batch[f.name]
        v = np.asarray(col.values, np.int64)
        p = self.get("period")
        if p == "HourOfDay":
            out = (v % _MS_DAY) // _MS_HOUR
        elif p == "DayOfWeek":
            out = ((v + _EPOCH_DOW_SHIFT) % _MS_WEEK) // _MS_DAY + 1
        elif p == "DayOfMonth":
            out = (v % int(30.44 * _MS_DAY)) // _MS_DAY + 1
        elif p == "DayOfYear":
            out = (v % _MS_YEAR) // _MS_DAY + 1
        elif p == "WeekOfYear":
            out = (v % _MS_YEAR) // _MS_WEEK + 1
        elif p == "MonthOfYear":
            out = (v % _MS_YEAR) // int(30.44 * _MS_DAY) + 1
        else:
            raise ValueError(f"unknown period {p}")
        return Column(Integral, out, mask=col.mask)


class DateListVectorizerModel(TransformerModel):
    out_kind = OPVector
    is_device_op = False

    def transform(self, batch: ColumnBatch) -> Column:
        pivot = self.get("pivot")
        ref = self.get("reference_ms")
        outs = []
        for f in self.input_features:
            lists = batch[f.name].values
            if pivot in ("SinceFirst", "SinceLast"):
                pick = min if pivot == "SinceFirst" else max
                vals, mask = [], []
                for lst in lists:
                    if lst:
                        vals.append((ref - pick(lst)) / _MS_DAY)
                        mask.append(True)
                    else:
                        vals.append(0.0)
                        mask.append(False)
                outs.append(np.asarray(vals, np.float32)[:, None])
                if self.get("track_nulls", True):
                    outs.append((~np.asarray(mask, bool)).astype(np.float32)[:, None])
            else:  # ModeDay / ModeMonth / ModeHour pivots one-hot the mode
                period = {"ModeDay": ("DayOfWeek", 7), "ModeMonth": ("MonthOfYear", 12),
                          "ModeHour": ("HourOfDay", 24)}[pivot]
                name, width = period
                block = np.zeros((len(lists), width), np.float32)
                for i, lst in enumerate(lists):
                    if not lst:
                        continue
                    from collections import Counter
                    cnt = Counter()
                    for ms in lst:
                        if name == "DayOfWeek":
                            cnt[int(((ms + _EPOCH_DOW_SHIFT) % _MS_WEEK) // _MS_DAY)] += 1
                        elif name == "MonthOfYear":
                            cnt[int((ms % _MS_YEAR) // int(30.44 * _MS_DAY)) % 12] += 1
                        else:
                            cnt[int((ms % _MS_DAY) // _MS_HOUR)] += 1
                    block[i, cnt.most_common(1)[0][0]] = 1.0
                outs.append(block)
        arr = np.concatenate(outs, axis=1)
        return Column(OPVector, jnp.asarray(arr), meta=self.fitted["meta"])


class DateListVectorizer(Estimator):
    """DateList pivots (≙ DateListVectorizer.scala): SinceFirst/SinceLast days
    or mode-of-period one-hot."""

    out_kind = OPVector

    def __init__(self, pivot: str = "SinceLast",
                 reference_ms: int = 1500000000000, track_nulls: bool = True,
                 **params):
        super().__init__(pivot=pivot, reference_ms=reference_ms,
                         track_nulls=track_nulls, **params)

    def fit(self, batch: ColumnBatch) -> TransformerModel:
        cols_meta: List[VectorColumnMeta] = []
        pivot = self.get("pivot")
        for f in self.input_features:
            if pivot in ("SinceFirst", "SinceLast"):
                cols_meta.append(VectorColumnMeta(
                    f.name, f.kind.__name__, descriptor_value=pivot))
                if self.get("track_nulls", True):
                    cols_meta.append(VectorColumnMeta(
                        f.name, f.kind.__name__, indicator_value=NULL_INDICATOR))
            else:
                width = {"ModeDay": 7, "ModeMonth": 12, "ModeHour": 24}[pivot]
                for j in range(width):
                    cols_meta.append(VectorColumnMeta(
                        f.name, f.kind.__name__,
                        descriptor_value=f"{pivot}_{j}"))
        meta = VectorMeta(self.output_name(), cols_meta)
        return self._finalize_model(DateListVectorizerModel(
            fitted={"meta": meta}, **self.params))
