from .numeric import (BinaryVectorizer, IntegralVectorizer, RealNNVectorizer,
                      RealVectorizer)
from .bucketizers import (DecisionTreeNumericBucketizer,
                          DecisionTreeNumericMapBucketizer,
                          DescalerTransformer, IsotonicRegressionCalibrator,
                          NumericBucketizer, PercentileCalibrator,
                          ScalerTransformer)
from .categorical import OneHotEstimator, StringIndexer, IndexToString
from .combiner import VectorsCombiner
from .transmogrify import transmogrify, TransmogrifierDefaults

__all__ = ["RealVectorizer", "RealNNVectorizer", "IntegralVectorizer",
           "BinaryVectorizer", "OneHotEstimator", "StringIndexer",
           "IndexToString", "VectorsCombiner", "transmogrify",
           "TransmogrifierDefaults", "NumericBucketizer",
           "DecisionTreeNumericBucketizer", "DecisionTreeNumericMapBucketizer",
           "PercentileCalibrator", "ScalerTransformer", "DescalerTransformer",
           "IsotonicRegressionCalibrator"]
