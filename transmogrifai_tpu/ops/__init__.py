from .numeric import (BinaryVectorizer, IntegralVectorizer, RealNNVectorizer,
                      RealVectorizer)
from .bucketizers import (DecisionTreeNumericBucketizer,
                          DecisionTreeNumericMapBucketizer,
                          DescalerTransformer, IsotonicRegressionCalibrator,
                          NumericBucketizer, PercentileCalibrator,
                          ScalerTransformer)
from .categorical import OneHotEstimator, StringIndexer, IndexToString
from .combiner import VectorsCombiner
from .transmogrify import transmogrify, TransmogrifierDefaults
from .text_specialized import (EmailMapToPickListMapTransformer,
                               EmailToPickListTransformer, HumanNameDetector,
                               IsValidPhoneDefaultCountry,
                               IsValidPhoneMapDefaultCountry, JaccardSimilarity,
                               LangDetector, MimeTypeDetector,
                               MimeTypeMapDetector, NameEntityRecognizer,
                               OpCountVectorizer, OpLDA, OpNGram,
                               OpStopWordsRemover, OpWord2Vec,
                               ParsePhoneDefaultCountry, SetNGramSimilarity,
                               TextNGramSimilarity, UrlMapToPickListMapTransformer,
                               UrlToPickListTransformer, ValidEmailTransformer)

__all__ = ["RealVectorizer", "RealNNVectorizer", "IntegralVectorizer",
           "BinaryVectorizer", "OneHotEstimator", "StringIndexer",
           "IndexToString", "VectorsCombiner", "transmogrify",
           "TransmogrifierDefaults", "NumericBucketizer",
           "DecisionTreeNumericBucketizer", "DecisionTreeNumericMapBucketizer",
           "PercentileCalibrator", "ScalerTransformer", "DescalerTransformer",
           "IsotonicRegressionCalibrator", "ValidEmailTransformer",
           "EmailToPickListTransformer", "EmailMapToPickListMapTransformer",
           "UrlToPickListTransformer", "UrlMapToPickListMapTransformer",
           "ParsePhoneDefaultCountry", "IsValidPhoneDefaultCountry",
           "IsValidPhoneMapDefaultCountry", "MimeTypeDetector",
           "MimeTypeMapDetector", "OpCountVectorizer", "OpNGram",
           "OpStopWordsRemover", "TextNGramSimilarity", "SetNGramSimilarity",
           "JaccardSimilarity", "LangDetector", "NameEntityRecognizer",
           "HumanNameDetector", "OpLDA", "OpWord2Vec"]
