from .numeric import (BinaryVectorizer, IntegralVectorizer, RealNNVectorizer,
                      RealVectorizer)
from .categorical import OneHotEstimator, StringIndexer, IndexToString
from .combiner import VectorsCombiner
from .transmogrify import transmogrify, TransmogrifierDefaults

__all__ = ["RealVectorizer", "RealNNVectorizer", "IntegralVectorizer",
           "BinaryVectorizer", "OneHotEstimator", "StringIndexer",
           "IndexToString", "VectorsCombiner", "transmogrify",
           "TransmogrifierDefaults"]
