"""Numeric vectorizers (reference: core/.../stages/impl/feature/
{RealVectorizer,IntegralVectorizer,BinaryVectorizer,RealNNVectorizer}.scala and
OpScalarStandardScaler, NumericBucketizer).

Fit = XLA reduction (masked mean / mode); transform = pure jnp fill +
null-indicator concat.  These are sequence stages: one stage vectorizes many
features of the same kind into a single [N, D] block with per-column lineage
metadata, matching the reference's SequenceEstimator design.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..columns import Column, ColumnBatch, to_device_f32
from ..stages.base import Estimator, Transformer, TransformerModel
from ..types import Binary, Integral, OPNumeric, OPVector, Real, RealNN
from ..vector_meta import NULL_INDICATOR, VectorColumnMeta, VectorMeta


def _masked_f32(col: Column):
    v = to_device_f32(col.values)
    m = col.mask
    m = jnp.ones(v.shape[0], bool) if m is None else jnp.asarray(m)
    return v, m


@jax.jit
def _masked_means(vs, ms):
    """All columns' masked means in ONE compiled reduction (one executable
    load + one dispatch instead of one per feature)."""
    return jnp.stack([
        jnp.where(m, jnp.nan_to_num(v), 0.0).sum() / jnp.maximum(m.sum(), 1)
        for v, m in zip(vs, ms)])


class RealVectorizerModel(TransformerModel):
    out_kind = OPVector

    def transform(self, batch: ColumnBatch) -> Column:
        fills = self.fitted["fills"]  # [F]
        track_nulls = self.get("track_nulls", True)
        outs = []
        for i, f in enumerate(self.input_features):
            v, m = _masked_f32(batch[f.name])
            filled = jnp.where(m, jnp.nan_to_num(v), fills[i])
            outs.append(filled[:, None])
            if track_nulls:
                outs.append((~m).astype(jnp.float32)[:, None])
        return Column(OPVector, jnp.concatenate(outs, axis=1), meta=self.fitted["meta"])


class RealVectorizer(Estimator):
    """Fill missing reals with the train-mean (or constant) + null indicator
    (≙ RealVectorizer.scala).  fill_mode: 'mean' | 'constant'."""

    in_kinds = None
    out_kind = OPVector

    def __init__(self, fill_mode: str = "mean", fill_value: float = 0.0,
                 track_nulls: bool = True, **params):
        super().__init__(fill_mode=fill_mode, fill_value=fill_value,
                         track_nulls=track_nulls, **params)

    def fit(self, batch: ColumnBatch) -> TransformerModel:
        cols_meta: List[VectorColumnMeta] = []
        for f in self.input_features:
            cols_meta.append(VectorColumnMeta(f.name, f.kind.__name__))
            if self.get("track_nulls", True):
                cols_meta.append(VectorColumnMeta(
                    f.name, f.kind.__name__, indicator_value=NULL_INDICATOR))
        if self.get("fill_mode") == "mean":
            pairs = [_masked_f32(batch[f.name]) for f in self.input_features]
            fills = _masked_means(tuple(v for v, _ in pairs),
                                  tuple(m for _, m in pairs))
        else:
            fills = jnp.full(len(self.input_features),
                             float(self.get("fill_value")), jnp.float32)
        meta = VectorMeta(self.output_name(), cols_meta)
        model = RealVectorizerModel(fitted={
            "fills": fills, "meta": meta}, **self.params)
        return self._finalize_model(model)


class RealNNVectorizerModel(TransformerModel):
    out_kind = OPVector

    def transform(self, batch: ColumnBatch) -> Column:
        outs = [to_device_f32(batch[f.name].values)[:, None]
                for f in self.input_features]
        return Column(OPVector, jnp.concatenate(outs, axis=1), meta=self.fitted["meta"])


class RealNNVectorizer(Estimator):
    """Non-nullable reals: straight passthrough into the vector
    (≙ RealNNVectorizer.scala)."""

    out_kind = OPVector

    def fit(self, batch: ColumnBatch) -> TransformerModel:
        meta = VectorMeta(self.output_name(), [
            VectorColumnMeta(f.name, f.kind.__name__) for f in self.input_features])
        return self._finalize_model(RealNNVectorizerModel(fitted={"meta": meta}))


class IntegralVectorizerModel(RealVectorizerModel):
    pass


class IntegralVectorizer(Estimator):
    """Fill missing integrals with train-mode (most frequent value)
    (≙ IntegralVectorizer.scala)."""

    out_kind = OPVector

    def __init__(self, fill_mode: str = "mode", fill_value: int = 0,
                 track_nulls: bool = True, **params):
        super().__init__(fill_mode=fill_mode, fill_value=fill_value,
                         track_nulls=track_nulls, **params)

    def fit(self, batch: ColumnBatch) -> TransformerModel:
        fills = []
        cols_meta: List[VectorColumnMeta] = []
        for f in self.input_features:
            col = batch[f.name]
            vals = np.asarray(col.values)
            m = np.ones(len(vals), bool) if col.mask is None else np.asarray(col.mask)
            if self.get("fill_mode") == "mode" and m.any():
                uniq, counts = np.unique(vals[m], return_counts=True)
                fill = float(uniq[np.argmax(counts)])
            else:
                fill = float(self.get("fill_value"))
            fills.append(fill)
            cols_meta.append(VectorColumnMeta(f.name, f.kind.__name__))
            if self.get("track_nulls", True):
                cols_meta.append(VectorColumnMeta(
                    f.name, f.kind.__name__, indicator_value=NULL_INDICATOR))
        meta = VectorMeta(self.output_name(), cols_meta)
        model = IntegralVectorizerModel(fitted={
            "fills": jnp.asarray(fills, jnp.float32), "meta": meta}, **self.params)
        return self._finalize_model(model)


class BinaryVectorizerModel(TransformerModel):
    out_kind = OPVector

    def transform(self, batch: ColumnBatch) -> Column:
        outs = []
        for f in self.input_features:
            col = batch[f.name]
            v = to_device_f32(col.values)
            m = (jnp.ones(v.shape[0], bool) if col.mask is None
                 else jnp.asarray(col.mask))
            outs.append(jnp.where(m, v, 0.0)[:, None])
            if self.get("track_nulls", True):
                outs.append((~m).astype(jnp.float32)[:, None])
        return Column(OPVector, jnp.concatenate(outs, axis=1), meta=self.fitted["meta"])


class BinaryVectorizer(Estimator):
    """Booleans → {0,1} + null indicator (≙ BinaryVectorizer.scala)."""

    out_kind = OPVector

    def __init__(self, track_nulls: bool = True, **params):
        super().__init__(track_nulls=track_nulls, **params)

    def fit(self, batch: ColumnBatch) -> TransformerModel:
        cols_meta: List[VectorColumnMeta] = []
        for f in self.input_features:
            cols_meta.append(VectorColumnMeta(f.name, f.kind.__name__))
            if self.get("track_nulls", True):
                cols_meta.append(VectorColumnMeta(
                    f.name, f.kind.__name__, indicator_value=NULL_INDICATOR))
        meta = VectorMeta(self.output_name(), cols_meta)
        return self._finalize_model(BinaryVectorizerModel(
            fitted={"meta": meta}, **self.params))


class StandardScalerModel(TransformerModel):
    out_kind = OPVector

    def transform(self, batch: ColumnBatch) -> Column:
        (col,) = self.input_columns(batch)
        v = to_device_f32(col.values)
        if v.ndim == 1:
            v = v[:, None]
        out = (jnp.nan_to_num(v) - self.fitted["mean"]) / self.fitted["std"]
        if col.mask is not None:
            out = jnp.where(jnp.asarray(col.mask)[:, None], out, 0.0)
        return Column(OPVector, out, meta=col.meta or self.fitted["meta"])


class StandardScaler(Estimator):
    """z-score scaling of a numeric/vector feature (≙ OpScalarStandardScaler)."""

    out_kind = OPVector

    def __init__(self, with_mean: bool = True, with_std: bool = True, **params):
        super().__init__(with_mean=with_mean, with_std=with_std, **params)

    def fit(self, batch: ColumnBatch) -> TransformerModel:
        (f,) = self.input_features
        col = batch[f.name]
        v = to_device_f32(col.values)
        if v.ndim == 1:
            v = v[:, None]
        # masked moments: missing entries (mask=False, stored as NaN/0) must
        # not poison the statistics
        if col.mask is not None:
            m = jnp.asarray(col.mask)[:, None].astype(jnp.float32)
            vz = jnp.nan_to_num(v) * m
            cnt = jnp.maximum(m.sum(axis=0), 1.0)
            mean_all = vz.sum(axis=0) / cnt
            var_all = (vz * vz).sum(axis=0) / cnt - mean_all ** 2
            std_all = jnp.sqrt(jnp.maximum(var_all, 0.0))
        else:
            mean_all = v.mean(axis=0)
            std_all = v.std(axis=0)
        mean = mean_all if self.get("with_mean", True) else jnp.zeros(v.shape[1])
        std = std_all if self.get("with_std", True) else jnp.ones(v.shape[1])
        std = jnp.where(std == 0, 1.0, std)
        meta = col.meta or VectorMeta(self.output_name(), [
            VectorColumnMeta(f.name, f.kind.__name__)])
        return self._finalize_model(StandardScalerModel(
            fitted={"mean": mean, "std": std, "meta": meta}, **self.params))
